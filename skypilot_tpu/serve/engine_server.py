"""HTTP model server wrapping serve/engine.py — the replica workload.

The reference's serve replicas run arbitrary user commands (vLLM,
JetStream, TGI — llm/mixtral/serve.yaml); readiness is probed over HTTP
(reference sky/serve/replica_managers.py:1026-1130) and clients speak
the OpenAI API (reference llm/mixtral/serve.yaml:37-40 probes
/v1/chat/completions). This server is the in-framework equivalent
workload: start it as the `run:` command of a service task and point
`readiness_probe: /health` (or /v1/models) at it.

Endpoints:
    GET  /health               -> 200 once the engine compiled a step
    GET  /v1/models            -> OpenAI model listing
    POST /generate             -> {"prompt": [ids] | "text",
                                  "max_new_tokens": N}
                                  returns {"tokens": [...], "text": ...}
    POST /v1/completions       -> OpenAI text completion (prompt as str
                                  or [ids]); "stream": true for SSE
    POST /v1/chat/completions  -> OpenAI chat (messages), rendered
                                  through the checkpoint's chat template
                                  when it ships one; SSE streaming

All streaming uses Server-Sent Events ending with `data: [DONE]`,
tokens emitted the moment the engine's decode loop produces them.

Tokenization: with --hf-model the checkpoint's OWN tokenizer is loaded
(serve/tokenizer.py); if the checkpoint ships no tokenizer asset, text
prompts are REJECTED (400) rather than garbled through a byte fallback
— ids 3..258 are arbitrary BPE tokens in a trained vocabulary. The
byte-level tokenizer remains the default for the random-weight demo
presets, where no real vocabulary exists.
"""
from __future__ import annotations

import argparse
import http.server
import json
import os
import queue
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax

from skypilot_tpu import sky_logging
from skypilot_tpu.models import gemma
from skypilot_tpu.models import llama
from skypilot_tpu.models import mixtral
from skypilot_tpu.serve import engine as engine_lib
from skypilot_tpu.serve import tokenizer as tokenizer_lib

logger = sky_logging.init_logger(__name__)

PAD_ID, BOS_ID, EOS_ID = (tokenizer_lib.PAD_ID, tokenizer_lib.BOS_ID,
                          tokenizer_lib.EOS_ID)

_BYTE_TOKENIZER = tokenizer_lib.ByteTokenizer()


def encode_text(text: str) -> List[int]:
    return _BYTE_TOKENIZER.encode(text)


def decode_tokens(tokens: Sequence[int]) -> str:
    return _BYTE_TOKENIZER.decode(tokens)


# name -> (config factory, model module implementing the serving
# contract — see serve/engine.py Engine docstring).
MODEL_PRESETS = {
    'tiny': (llama.llama_tiny, llama),
    'llama3-1b': (llama.llama3_1b, llama),
    'llama3-8b': (llama.llama3_8b, llama),
    'qwen2-7b': (llama.qwen2_7b, llama),
    'gemma-2b': (gemma.gemma_2b, llama),
    'gemma-7b': (gemma.gemma_7b, llama),
    'gemma-tiny': (gemma.gemma_tiny, llama),
    'mixtral-tiny': (mixtral.mixtral_tiny, mixtral),
    'mixtral-8x7b': (mixtral.mixtral_8x7b, mixtral),
}


class _BadRequest(ValueError):
    pass


def _parse_logit_bias(raw) -> Optional[Dict[int, float]]:
    """OpenAI wire form: {"<token id>": bias}. Anything else is a
    loud 400, not a handler-thread traceback."""
    if raw is None:
        return None
    if not hasattr(raw, 'items'):
        raise _BadRequest(
            'logit_bias must be an object mapping token ids to bias '
            'values')
    try:
        return {int(k): float(v) for k, v in raw.items()} or None
    except (TypeError, ValueError) as e:
        raise _BadRequest(f'malformed logit_bias: {e}')


def _first_stop_match(text: str, stop: Optional[List[str]]) -> int:
    """Offset of the earliest stop-string match in `text`, or -1. The
    single matcher both the plain and streaming paths use — they must
    agree on where a completion ends."""
    if not stop:
        return -1
    hits = [i for i in (text.find(s) for s in stop) if i >= 0]
    return min(hits) if hits else -1


class ModelServer:

    @classmethod
    def from_engine(cls, engine: 'engine_lib.Engine', port: int,
                    tokenizer: Optional[Any] = _BYTE_TOKENIZER,
                    model_name: str = 'model') -> 'ModelServer':
        """Wrap an already-built Engine (tests / embedding use): the
        HTTP surface without __init__'s model construction."""
        srv = cls.__new__(cls)
        srv.engine = engine
        srv.tokenizer = tokenizer
        srv.model_name = model_name
        srv.port = port
        srv.ready = threading.Event()
        srv.request_queue = queue.Queue()
        srv.stop = threading.Event()
        srv._httpd = None
        return srv

    def __init__(self, model: str = 'tiny', port: int = 8000,
                 batch_size: int = 8, max_decode_len: int = 1024,
                 temperature: float = 0.0,
                 quantize: Optional[str] = None,
                 tp: int = 1,
                 hf_model: Optional[str] = None,
                 kv_quantize: Optional[str] = None,
                 ckpt: Optional[str] = None,
                 prefix_cache: int = 0,
                 online_decode_chunk: int = 1,
                 prefill_chunk: int = 0):
        params = None
        eos_id = EOS_ID

        def adopt_checkpoint(path: str, ckpt_eos) -> int:
            """Shared checkpoint-adoption tail: load the checkpoint's
            tokenizer and resolve EOS — the checkpoint's declared EOS
            (may be a multi-EOS tuple) wins, else the tokenizer's, else
            the byte default (a Llama-3 vocab uses byte id 2 as an
            ordinary BPE token, so the fallbacks matter)."""
            self.tokenizer = tokenizer_lib.load_tokenizer(path)
            self.model_name = path
            if self.tokenizer is None:
                logger.warning(
                    'checkpoint %s ships no tokenizer asset: text '
                    'prompts will be rejected (pass token ids)', path)
            if ckpt_eos is not None:
                return ckpt_eos
            if (self.tokenizer is not None
                    and self.tokenizer.eos_id is not None):
                # config without an EOS declaration: the tokenizer
                # assets still know the real EOS.
                return self.tokenizer.eos_id
            return EOS_ID

        if ckpt is not None:
            # Native serving checkpoint (orbax + model_config.json +
            # tokenizer assets — models/native_ckpt.py): the output of
            # finetune_lora.py --merge-out, served without an HF round
            # trip.
            from skypilot_tpu.models import native_ckpt
            model_module, cfg, params, nk_eos = (
                native_ckpt.load_serving_ckpt(ckpt))
            eos_id = adopt_checkpoint(ckpt, nk_eos)
        elif hf_model is not None:
            # Real checkpoint path (local dir or GCS mount): convert a
            # transformers LlamaForCausalLM to our functional params
            # (models/hf_convert.py); `model` preset is ignored.
            # torch_dtype='auto' keeps the checkpoint dtype on the host
            # (an 8B bf16 checkpoint would otherwise load as 32 GB of
            # fp32 torch tensors before conversion).
            from skypilot_tpu.models import hf_convert
            model_module, cfg, params, hf_eos = hf_convert.from_hf_auto(
                hf_model)
            eos_id = adopt_checkpoint(hf_model, hf_eos)
        else:
            cfg_factory, model_module = MODEL_PRESETS[model]
            cfg = cfg_factory()
            self.tokenizer = _BYTE_TOKENIZER
            self.model_name = model
        mesh = None
        if tp > 1:
            from skypilot_tpu.parallel import mesh as mesh_lib
            mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(tp=tp),
                                      devices=jax.devices()[:tp])
        self.engine = engine_lib.Engine(
            cfg, params, model=model_module, mesh=mesh,
            engine_cfg=engine_lib.EngineConfig(
                batch_size=batch_size, max_decode_len=max_decode_len,
                eos_id=eos_id, temperature=temperature,
                quantize=quantize, kv_quantize=kv_quantize,
                prefix_cache=prefix_cache,
                online_decode_chunk=online_decode_chunk,
                prefill_chunk=prefill_chunk))
        self.port = port
        self.ready = threading.Event()
        self.request_queue: queue.Queue = queue.Queue()
        self.stop = threading.Event()
        self._httpd = None

    def _warmup(self) -> None:
        first, _logp, kv = self.engine.prefill([BOS_ID])
        self.engine.insert(kv, 0, 1, first)
        self.engine.decode()
        # Reset state after warm-up compile.
        self.engine._lengths = self.engine._lengths * 0
        self.ready.set()
        logger.info('engine warmed up; serving on :%d', self.port)

    # -- request parsing ---------------------------------------------- #

    def _encode_prompt(self, prompt: Any) -> List[int]:
        if isinstance(prompt, str):
            if self.tokenizer is None:
                raise _BadRequest(
                    'this checkpoint has no tokenizer: pass token ids '
                    '(a string prompt cannot be encoded faithfully)')
            return self.tokenizer.encode(prompt)
        if isinstance(prompt, list) and all(
                isinstance(t, int) or (isinstance(t, float)
                                       and float(t).is_integer())
                for t in prompt):
            return [int(t) for t in prompt]
        raise _BadRequest('prompt must be a string or a list of ints')

    def _sampling_from(self, req: Dict[str, Any]
                       ) -> Optional[engine_lib.SamplingParams]:
        if not any(k in req for k in
                   ('temperature', 'top_k', 'top_p',
                    'frequency_penalty', 'presence_penalty',
                    'logit_bias', 'seed')):
            return None
        # Unspecified fields keep the SERVER's defaults (a request
        # asking only for top_p must not silently flip the temperature
        # to greedy).
        sp = engine_lib.SamplingParams(
            temperature=float(req.get('temperature',
                                      self.engine.cfg.temperature)),
            top_k=int(req.get('top_k', 0)),
            top_p=float(req.get('top_p', 1.0)),
            frequency_penalty=float(req.get('frequency_penalty', 0.0)),
            presence_penalty=float(req.get('presence_penalty', 0.0)),
            # OpenAI sends {"<token id as string>": bias}; normalize
            # to int keys (validate_sampling checks range and count).
            logit_bias=_parse_logit_bias(req.get('logit_bias')),
            seed=(int(req['seed']) if req.get('seed') is not None
                  else None))
        # Loud validation at the API boundary (engine re-validates):
        # silently clamping top_k>64 to 64 surprised clients.
        self.engine.validate_sampling(sp)
        return sp

    def _decode_text(self, toks: List[int]) -> str:
        return self.tokenizer.decode(toks) if self.tokenizer else ''

    def _token_strs(self, toks: List[int]) -> List[str]:
        """Per-token text as incremental-decode DIFFS: the strings
        concatenate exactly to decode(toks) (isolated per-id decode
        loses BPE word-boundary spacing)."""
        if self.tokenizer is None:
            return ['' for _ in toks]
        dec = tokenizer_lib.StreamDecoder(self.tokenizer)
        out = [dec.push(t) for t in toks]
        if out:
            out[-1] += dec.flush()
        return out

    # -- server ------------------------------------------------------- #

    def serve_forever(self) -> None:
        server = self

        class Handler(http.server.BaseHTTPRequestHandler):
            # HTTP/1.1 + explicit framing on every response (length or
            # chunked) so streams pass through proxies correctly.
            protocol_version = 'HTTP/1.1'

            def log_message(self, *args):
                pass

            def _json(self, code: int, payload) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header('Content-Type', 'application/json')
                self.send_header('Content-Length', str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _error(self, code: int, msg: str) -> None:
                # OpenAI-style error envelope (also fine for /generate).
                self._json(code, {'error': {'message': msg,
                                            'type': 'invalid_request_error'}
                                  if self.path.startswith('/v1/')
                                  else msg})

            def do_GET(self):
                if self.path == '/health':
                    if server.ready.is_set():
                        self._json(200, {'status': 'ok'})
                    else:
                        self._json(503, {'status': 'warming up'})
                elif self.path == '/v1/models':
                    self._json(200, {
                        'object': 'list',
                        'data': [{'id': server.model_name,
                                  'object': 'model',
                                  'owned_by': 'skypilot-tpu'}]})
                else:
                    self._error(404, 'not found')

            def do_POST(self):
                route = {
                    '/generate': self._handle_generate,
                    '/v1/completions': self._handle_completions,
                    '/v1/chat/completions': self._handle_chat,
                }.get(self.path)
                if route is None:
                    self._error(404, 'not found')
                    return
                length = int(self.headers.get('Content-Length', 0))
                try:
                    req = json.loads(self.rfile.read(length) or b'{}')
                    if not isinstance(req, dict):
                        raise _BadRequest('request body must be a JSON '
                                          'object')
                    route(req)
                except (_BadRequest, ValueError, TypeError, KeyError,
                        json.JSONDecodeError) as e:
                    self._error(400, str(e))

            # -- request execution ------------------------------------ #

            def _enqueue(self, tokens: List[int], max_new: int,
                         sampling) -> 'queue.Queue':
                out_q: queue.Queue = queue.Queue()
                server.request_queue.put(
                    (tokens, max_new, out_q, sampling))
                return out_q

            def _collect(self, out_q: 'queue.Queue'):
                """Drain a stream queue of (token, logprob) pairs."""
                toks: List[int] = []
                logps: List[float] = []
                error = None
                while True:
                    item = out_q.get()
                    if item is None:
                        break
                    if isinstance(item, Exception):
                        error = item
                        continue
                    tok, logp = item
                    toks.append(tok)
                    logps.append(logp)
                return toks, logps, error

            # -- /generate (legacy ids+text API) ---------------------- #

            def _handle_generate(self, req) -> None:
                tokens = server._encode_prompt(req.get('prompt'))
                max_new = int(req.get('max_new_tokens', 64))
                sampling = server._sampling_from(req)
                # Pre-validate so a stream request gets a real 400, not
                # an in-band error frame inside a 200 stream.
                server.engine._validate(tokens)
                out_q = self._enqueue(tokens, max_new, sampling)
                if bool(req.get('stream', False)):
                    # Final 'text'-only frame carries any tail the
                    # incremental detokenizer held back (a stream
                    # ending mid multi-byte character).
                    self._stream_sse(
                        out_q,
                        lambda tok, delta: {'token': tok, 'text': delta})
                    return
                toks, logps, error = self._collect(out_q)
                if error is not None:
                    self._error(400, str(error))
                    return
                self._json(200, {'tokens': toks,
                                 'logprobs': [round(p, 6)
                                              for p in logps],
                                 'text': server._decode_text(toks)})

            # -- OpenAI-compatible endpoints -------------------------- #

            def _handle_completions(self, req) -> None:
                tokens = server._encode_prompt(req.get('prompt'))
                self._run_openai(req, tokens, chat=False)

            def _handle_chat(self, req) -> None:
                messages = req.get('messages')
                if (not isinstance(messages, list) or not messages
                        or not all(isinstance(m, dict)
                                   for m in messages)):
                    raise _BadRequest(
                        'messages must be a non-empty list of '
                        '{role, content} objects')
                if server.tokenizer is None:
                    raise _BadRequest(
                        'this checkpoint has no tokenizer: chat '
                        'requests need one (serve with a checkpoint '
                        'directory that ships tokenizer assets)')
                tokens = server.tokenizer.apply_chat_template(messages)
                self._run_openai(req, tokens, chat=True)

            def _run_openai(self, req, tokens: List[int],
                            chat: bool) -> None:
                max_new = int(req.get('max_tokens',
                                      req.get('max_new_tokens', 64)))
                if (not chat and max_new == 0 and req.get('echo')
                        and req.get('logprobs')):
                    # Teacher-forced scoring (the lm-eval-harness
                    # loglikelihood path): no generation, just the
                    # prompt's own per-token logprobs.
                    self._score_prompt(req, tokens)
                    return
                if max_new <= 0:
                    raise _BadRequest(
                        'max_tokens must be positive (0 is valid only '
                        'with echo=true and logprobs for scoring)')
                sampling = server._sampling_from(req)
                stop = req.get('stop')
                if isinstance(stop, str):
                    stop = [stop]
                if stop is not None and not (
                        isinstance(stop, list)
                        and all(isinstance(s, str) and s
                                for s in stop)):
                    raise _BadRequest('stop must be a non-empty string '
                                      'or a list of non-empty strings')
                # Reject un-servable prompts BEFORE the stream opens:
                # once SSE headers are out, an engine-side rejection
                # can only surface as an in-band error frame, which a
                # client sees as a 200.
                server.engine._validate(tokens)
                want_logprobs = req.get('logprobs')
                if want_logprobs is not None and not isinstance(
                        want_logprobs, (bool, int)):
                    raise _BadRequest('logprobs must be a bool/int')
                if want_logprobs and bool(req.get('stream', False)):
                    raise _BadRequest(
                        'logprobs with stream=true is not supported '
                        '(token->text deltas do not map 1:1)')
                rid = (f'chatcmpl-{int(time.time()*1000)}' if chat
                       else f'cmpl-{int(time.time()*1000)}')
                created = int(time.time())
                stream_opts = req.get('stream_options', {})
                if not isinstance(stream_opts, dict):
                    raise _BadRequest('stream_options must be an object')
                if stream_opts and not bool(req.get('stream', False)):
                    raise _BadRequest(
                        'stream_options is only allowed when '
                        'stream is true')
                # OpenAI n / best_of: generate best_of completions,
                # return the n with the highest cumulative logprob
                # (chat has n only). All ride the same continuous
                # batch; usage counts every generated token, matching
                # the OpenAI billing semantics for best_of.
                n = int(req.get('n', 1))
                best_of = int(req.get('best_of', n))
                if chat and 'best_of' in req:
                    raise _BadRequest(
                        'best_of is not part of the chat API (use n)')
                if n < 1 or best_of < n:
                    raise _BadRequest(
                        f'need 1 <= n <= best_of, got n={n} '
                        f'best_of={best_of}')
                if best_of > 16:
                    raise _BadRequest('best_of is capped at 16')
                if best_of > 1 and bool(req.get('stream', False)):
                    # OpenAI also rejects best_of with streaming —
                    # silently streaming ONE un-ranked completion
                    # would look like best_of worked.
                    raise _BadRequest(
                        'n/best_of > 1 with stream=true is not '
                        'supported')
                out_q = self._enqueue(tokens, max_new, sampling)
                if bool(req.get('stream', False)):
                    self._stream_openai(
                        out_q, rid, created, chat, stop, max_new,
                        n_prompt=len(tokens),
                        include_usage=bool(
                            stream_opts.get('include_usage')))
                    return
                # best_of - 1 extra parallel generations (queue 0 was
                # enqueued above, before the stream branch). A seeded
                # request gets seed+i per extra copy — byte-identical
                # copies would make the logprob ranking (and the n>1
                # diversity the client asked for) meaningless.
                def copy_sampling(i):
                    if (sampling is not None
                            and sampling.seed is not None):
                        import dataclasses as _dc
                        return _dc.replace(sampling,
                                           seed=sampling.seed + i)
                    return sampling
                extra_qs = [self._enqueue(tokens, max_new,
                                          copy_sampling(i))
                            for i in range(1, best_of)]
                results = [self._collect(q)
                           for q in [out_q] + extra_qs]
                for _t, _l, error in results:
                    if error is not None:
                        self._error(400, str(error))
                        return

                # echo+logprobs prompt scoring is per-REQUEST: one
                # teacher-forced pass reused by every choice.
                echo_score = None
                if (not chat and req.get('echo') and want_logprobs):
                    echo_score = server.engine.score(tokens)

                def build_choice(index, toks, logps):
                    text = server._decode_text(toks)
                    finish = ('length' if len(toks) >= max_new
                              else 'stop')
                    cut = _first_stop_match(text, stop)
                    if cut >= 0:
                        text = text[:cut]
                        finish = 'stop'
                    logprobs_obj = None
                    if want_logprobs:
                        # A stop-sequence cut truncates the token list
                        # to the kept text.
                        token_strs = server._token_strs(toks)
                        kept_lps = [round(p, 6) for p in logps]
                        if cut >= 0:
                            kept, acc = [], 0
                            for ts in token_strs:
                                if acc >= len(text):
                                    break
                                kept.append(ts[:len(text) - acc])
                                acc += len(ts)
                            token_strs = kept
                            kept_lps = kept_lps[:len(kept)]
                        if chat:
                            # chat.completion logprobs schema.
                            logprobs_obj = {'content': [
                                {'token': ts, 'logprob': p}
                                for ts, p in zip(token_strs, kept_lps)]}
                        else:
                            # Legacy text-completion logprobs schema.
                            logprobs_obj = {
                                'tokens': token_strs,
                                'token_logprobs': kept_lps,
                                'top_logprobs': None,
                            }
                    if not chat and req.get('echo'):
                        # OpenAI echo semantics: the prompt is part of
                        # the returned text (and of the logprobs
                        # arrays, via the teacher-forced scoring pass).
                        text = server._decode_text(tokens) + text
                        if logprobs_obj is not None:
                            p_lps, p_ids, p_tops = echo_score
                            p_strs = server._token_strs(tokens)
                            logprobs_obj = {
                                'tokens':
                                    p_strs + logprobs_obj['tokens'],
                                'token_logprobs':
                                    [None] + [round(p, 6)
                                              for p in p_lps[1:]]
                                    + logprobs_obj['token_logprobs'],
                                'top_logprobs':
                                    [None] + [
                                        {server._decode_text([i]):
                                         round(p, 6)}
                                        for i, p in zip(p_ids[1:],
                                                        p_tops[1:])]
                                    + [None] * len(
                                        logprobs_obj['tokens']),
                            }
                    if chat:
                        return {'index': index,
                                'message': {'role': 'assistant',
                                            'content': text},
                                'logprobs': logprobs_obj,
                                'finish_reason': finish}
                    return {'index': index, 'text': text,
                            'logprobs': logprobs_obj,
                            'finish_reason': finish}

                # Rank by cumulative logprob (greedy duplicates tie —
                # order then keeps arrival order, like OpenAI).
                order = sorted(range(best_of),
                               key=lambda i: -sum(results[i][1]))
                choices = [build_choice(ci, results[i][0],
                                        results[i][1])
                           for ci, i in enumerate(order[:n])]
                obj = 'chat.completion' if chat else 'text_completion'
                gen_total = sum(len(t) for t, _l, _e in results)
                self._json(200, {
                    'id': rid, 'object': obj, 'created': created,
                    'model': server.model_name, 'choices': choices,
                    'usage': {'prompt_tokens': len(tokens),
                              'completion_tokens': gen_total,
                              'total_tokens': len(tokens) + gen_total}})

            def _score_prompt(self, req, tokens: List[int]) -> None:
                """echo=true, max_tokens=0, logprobs: per-token
                logprobs of the PROMPT itself (teacher-forced, one
                forward pass — no decode slots consumed)."""
                if bool(req.get('stream', False)):
                    raise _BadRequest('echo scoring does not stream')
                logps, top_ids, top_lps = server.engine.score(tokens)
                token_strs = server._token_strs(tokens)
                text = server._decode_text(tokens)
                offsets, acc = [], 0
                for ts in token_strs:
                    offsets.append(acc)
                    acc += len(ts)
                # top_logprobs: the argmax alternative per position —
                # loglikelihood clients compute `is_greedy` from it.
                tops = [None] + [
                    {server._decode_text([i]): round(p, 6)}
                    for i, p in zip(top_ids[1:], top_lps[1:])]
                self._json(200, {
                    'id': f'cmpl-{int(time.time()*1000)}',
                    'object': 'text_completion',
                    'created': int(time.time()),
                    'model': server.model_name,
                    'choices': [{
                        'index': 0, 'text': text,
                        'logprobs': {
                            'tokens': token_strs,
                            'token_logprobs':
                                [None] + [round(p, 6)
                                          for p in logps[1:]],
                            'top_logprobs': tops,
                            'text_offset': offsets,
                        },
                        'finish_reason': 'stop'}],
                    'usage': {'prompt_tokens': len(tokens),
                              'completion_tokens': 0,
                              'total_tokens': len(tokens)}})

            # -- streaming -------------------------------------------- #

            def _chunk(self, data: bytes) -> None:
                self.wfile.write(f'{len(data):x}\r\n'.encode() + data
                                 + b'\r\n')
                self.wfile.flush()

            def _sse_headers(self) -> None:
                self.send_response(200)
                self.send_header('Content-Type', 'text/event-stream')
                self.send_header('Cache-Control', 'no-cache')
                self.send_header('Transfer-Encoding', 'chunked')
                self.end_headers()

            def _stream_sse(self, out_q: 'queue.Queue',
                            make_payload) -> None:
                """Emit each token the moment the engine's decode loop
                produces it. `make_payload(token, text_delta)` builds
                the per-event JSON body; detokenization is incremental
                (BPE tokens don't map 1:1 to text)."""
                self._sse_headers()
                dec = (tokenizer_lib.StreamDecoder(server.tokenizer)
                       if server.tokenizer else None)
                try:
                    while True:
                        item = out_q.get()
                        if item is None:
                            break
                        if isinstance(item, Exception):
                            payload = {'error': str(item)}
                        else:
                            tok, _logp = item
                            delta = dec.push(tok) if dec else ''
                            payload = make_payload(tok, delta)
                        self._chunk(b'data: '
                                    + json.dumps(payload).encode()
                                    + b'\n\n')
                    if dec is not None:
                        tail = dec.flush()
                        if tail:
                            self._chunk(b'data: '
                                        + json.dumps({'text': tail}
                                                     ).encode()
                                        + b'\n\n')
                    self._chunk(b'data: [DONE]\n\n')
                    self._chunk(b'')  # terminating 0-length chunk
                except OSError:
                    # Client went away mid-stream (BrokenPipe /
                    # ConnectionReset / other socket errors are all
                    # OSError); the engine finishes into the orphaned
                    # queue harmlessly.
                    pass

            def _stream_openai(self, out_q: 'queue.Queue', rid: str,
                               created: int, chat: bool,
                               stop: Optional[List[str]],
                               max_new: int, n_prompt: int = 0,
                               include_usage: bool = False) -> None:
                """OpenAI SSE chunk framing. Stop sequences are matched
                host-side on the cumulative text; text that could still
                be the PREFIX of a stop string is held back (a stop
                string spanning two deltas must not leak its first
                half), so stream and non-stream agree. On a match the
                stream ends early (the engine finishes into the
                orphaned queue). With stream_options.include_usage
                (OpenAI parity) a final usage chunk with empty
                `choices` precedes [DONE] — the only faithful token
                count a streaming client can get, since text deltas do
                not map 1:1 to tokens (a multi-byte UTF-8 token can
                buffer in the incremental decoder and emit no frame)."""
                self._sse_headers()
                obj = 'chat.completion.chunk' if chat else 'text_completion'

                def frame(delta_text: Optional[str], finish) -> bytes:
                    if chat:
                        delta = ({'content': delta_text}
                                 if delta_text is not None else {})
                        choice = {'index': 0, 'delta': delta,
                                  'finish_reason': finish}
                    else:
                        choice = {'index': 0, 'text': delta_text or '',
                                  'logprobs': None,
                                  'finish_reason': finish}
                    return b'data: ' + json.dumps(
                        {'id': rid, 'object': obj, 'created': created,
                         'model': server.model_name,
                         'choices': [choice]}).encode() + b'\n\n'

                dec = (tokenizer_lib.StreamDecoder(server.tokenizer)
                       if server.tokenizer else None)
                hold = max((len(s) for s in stop), default=0) - 1 \
                    if stop else 0
                pending = ''
                n_tokens = 0
                stopped = False
                try:
                    if chat:
                        # Role announcement chunk (OpenAI convention).
                        self._chunk(b'data: ' + json.dumps(
                            {'id': rid, 'object': obj,
                             'created': created,
                             'model': server.model_name,
                             'choices': [{'index': 0,
                                          'delta': {'role': 'assistant'},
                                          'finish_reason': None}]}
                        ).encode() + b'\n\n')
                    while True:
                        item = out_q.get()
                        if item is None:
                            break
                        if isinstance(item, Exception):
                            self._chunk(b'data: ' + json.dumps(
                                {'error': str(item)}).encode()
                                + b'\n\n')
                            continue
                        n_tokens += 1
                        tok, _logp = item
                        delta = dec.push(tok) if dec else ''
                        if stop:
                            pending += delta
                            cut = _first_stop_match(pending, stop)
                            if cut >= 0:
                                if cut > 0:
                                    self._chunk(frame(pending[:cut],
                                                      None))
                                stopped = True
                                break
                            # Emit all but the last `hold` chars: the
                            # held tail could still start a stop match.
                            n_emit = len(pending) - hold
                            if n_emit > 0:
                                self._chunk(frame(pending[:n_emit],
                                                  None))
                                pending = pending[n_emit:]
                        elif delta or not dec:
                            self._chunk(frame(delta, None))
                    if not stopped:
                        tail = dec.flush() if dec else ''
                        pending += tail
                        cut = _first_stop_match(pending, stop)
                        if cut >= 0:
                            pending = pending[:cut]
                            stopped = True
                        if pending:
                            self._chunk(frame(pending, None))
                    finish = ('length' if n_tokens >= max_new
                              and not stopped else 'stop')
                    self._chunk(frame(None, finish))
                    if include_usage:
                        self._chunk(b'data: ' + json.dumps(
                            {'id': rid, 'object': obj,
                             'created': created,
                             'model': server.model_name,
                             'choices': [],
                             'usage': {
                                 'prompt_tokens': n_prompt,
                                 'completion_tokens': n_tokens,
                                 'total_tokens': n_prompt + n_tokens,
                             }}).encode() + b'\n\n')
                    self._chunk(b'data: [DONE]\n\n')
                    self._chunk(b'')
                except OSError:
                    pass

        class ThreadingServer(http.server.ThreadingHTTPServer):
            daemon_threads = True

        # Bind + listen BEFORE warmup so `ready` (set at the end of
        # warmup) guarantees connections are accepted — setting it while
        # the socket was still unbound made an immediate client connect
        # race warmup and fail with ECONNREFUSED.
        self._httpd = ThreadingServer(('0.0.0.0', self.port), Handler)
        try:
            self._warmup()
            loop = threading.Thread(
                target=self.engine.run_loop,
                args=(self.request_queue, self.stop), daemon=True)
            loop.start()
            self._httpd.serve_forever()
        finally:
            # Covers warmup failures too: the socket is bound before
            # warmup, and leaking it would EADDRINUSE the next bind in
            # this process (long-lived test runners).
            self.stop.set()
            self.request_queue.put(None)
            self._httpd.server_close()

    def shutdown(self) -> None:
        self.stop.set()
        if self._httpd is not None:
            self._httpd.shutdown()


def main() -> None:
    # Honor JAX_PLATFORMS=cpu even under the axon TPU tunnel plugin,
    # which self-registers regardless of the env var (same pin as
    # bench.py / __graft_entry__.py) — a CPU-pinned server must not
    # touch (or hang on) the tunnel.
    if os.environ.get('JAX_PLATFORMS') == 'cpu':
        jax.config.update('jax_platforms', 'cpu')
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('--model', default='tiny',
                        choices=sorted(MODEL_PRESETS))
    parser.add_argument('--port', type=int, default=8000)
    parser.add_argument('--batch-size', type=int, default=8)
    parser.add_argument('--max-decode-len', type=int, default=1024)
    parser.add_argument('--temperature', type=float, default=0.0)
    parser.add_argument('--quantize', choices=['int8'], default=None,
                        help='weight-only quantization (halves weight '
                             'HBM traffic; decode is weight-bound)')
    parser.add_argument('--kv-quantize', choices=['int8'], default=None,
                        help='int8 KV cache: halves cache HBM traffic '
                             'and residency (~2x decode slots per chip)')
    parser.add_argument('--tp', type=int, default=1,
                        help='tensor-parallel degree: shard the model '
                             'over this many chips (one SPMD program, '
                             'XLA collectives over ICI)')
    parser.add_argument('--hf-model', default=None,
                        help='path to a HuggingFace Llama or Mixtral '
                             'checkpoint (auto-detected, converted via '
                             'models/hf_convert.py; overrides --model; '
                             'loads the checkpoint tokenizer for the '
                             'text/chat endpoints)')
    parser.add_argument('--ckpt', default=None,
                        help='path to a native serving checkpoint '
                             '(models/native_ckpt.py — e.g. '
                             'finetune_lora.py --merge-out output); '
                             'overrides --model/--hf-model')
    parser.add_argument('--prefix-cache', type=int, default=0,
                        help='prefix-KV reuse: keep the KV of this '
                             'many recent prompts; requests sharing a '
                             'common prefix (shared system prompts) '
                             'prefill only the suffix (cuts TTFT). '
                             '0 disables.')
    parser.add_argument('--prefill-chunk', type=int, default=0,
                        help='chunked prefill: prompts longer than '
                             'this prefill in chunks of this size, '
                             'interleaved with decode steps, so a '
                             'long arrival cannot stall in-flight '
                             'streams for its whole prefill. '
                             '0 disables.')
    parser.add_argument('--online-decode-chunk', type=int, default=1,
                        help='fuse this many decode steps per host '
                             'round trip in the serving loop (tokens '
                             'stream in bursts of this size); raise '
                             'over high-RTT relays where per-token '
                             'syncs cap throughput')
    args = parser.parse_args()
    logger.info('devices: %s', jax.devices())
    ModelServer(args.model, args.port, args.batch_size,
                args.max_decode_len, args.temperature,
                args.quantize, args.tp, args.hf_model,
                args.kv_quantize, ckpt=args.ckpt,
                prefix_cache=args.prefix_cache,
                online_decode_chunk=args.online_decode_chunk,
                prefill_chunk=args.prefill_chunk
                ).serve_forever()


if __name__ == '__main__':
    main()
