"""Replica manager (reference: sky/serve/replica_managers.py, 1240 LoC —
SkyPilotReplicaManager: launch/terminate replica clusters + readiness
probing threads).
"""
from __future__ import annotations

import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.serve import state
from skypilot_tpu.serve.service_spec import SkyServiceSpec

logger = sky_logging.init_logger(__name__)

PROBE_FAILURE_THRESHOLD = 3


class ReplicaInfo:
    def __init__(self, replica_id: int, cluster_name: str,
                 port: int, is_spot: bool = False,
                 version: int = 1) -> None:
        self.replica_id = replica_id
        self.cluster_name = cluster_name
        self.port = port
        self.is_spot = is_spot
        self.version = version
        self.status = state.ReplicaStatus.PROVISIONING
        self.endpoint: Optional[str] = None
        self.consecutive_failures = 0
        self.first_ready_probe_at: Optional[float] = None
        self.launched_at = time.time()
        self.active_requests = 0   # LeastLoad policy counter (LB-owned)


class ReplicaManager:
    """Launch/terminate/probe replicas. Each replica is a full cluster
    launch (recursion into the launch stack, like the reference's
    _launch_replica via sky.launch, replica_managers.py:643)."""

    def __init__(self, service_name: str, task: task_lib.Task,
                 spec: SkyServiceSpec) -> None:
        self.service_name = service_name
        self.task = task
        self.spec = spec
        self.version = 1
        self.replicas: Dict[int, ReplicaInfo] = {}
        self._next_id = 1
        self._lock = threading.Lock()
        self._threads: List[threading.Thread] = []

    def adopt_existing_replicas(self) -> int:
        """Rebuild in-memory replica records from the serve DB after a
        controller restart (the daemon's ServeControllerEvent respawns a
        dead controller; without adoption the new process would leak the
        old replica clusters and launch fresh ones — the reference's
        replica manager recovers its replica set from serve_state the
        same way). Returns the number of adopted live replicas."""
        adopted = 0
        for row in state.get_replicas(self.service_name):
            rid = row['replica_id']
            self._next_id = max(self._next_id, rid + 1)
            # The persisted version marks pre-update replicas so an
            # interrupted blue-green rollout resumes after a controller
            # restart instead of being silently dropped.
            info = ReplicaInfo(rid, row['cluster_name'],
                               self._replica_port(rid),
                               is_spot=self.spec.use_spot,
                               version=row.get('version', 1))
            info.endpoint = row['endpoint']
            with self._lock:
                self.replicas[rid] = info
            if row['endpoint'] and row['status'] not in (
                    state.ReplicaStatus.SHUTTING_DOWN.value,
                    state.ReplicaStatus.FAILED.value):
                # Probes re-establish readiness before it serves again.
                info.status = state.ReplicaStatus.STARTING
                adopted += 1
            else:
                # Launch/teardown was in flight when the old controller
                # died; its thread is gone. Terminate the remnant (down
                # is a no-op when the cluster never came up).
                self.scale_down(rid)
        return adopted

    def begin_update(self, task: task_lib.Task, spec: SkyServiceSpec,
                     version: int) -> None:
        """`skyt serve update`: future launches use the new task/spec;
        rollout_tick replaces old-version replicas blue-green."""
        self.task = task
        self.spec = spec
        self.version = version

    @property
    def updating(self) -> bool:
        return any(i.version < self.version
                   for i in self.replicas.values())

    # -------------------------------------------------------------- #

    def _replica_port(self, replica_id: int) -> int:
        # On the fake (localhost) cloud every replica shares the host, so
        # each gets a unique port; real clouds use the spec port.
        if (self.task.resources.cloud or 'gcp') == 'fake':
            return self.spec.port + replica_id
        return self.spec.port

    def scale_up(self, use_spot: Optional[bool] = None) -> None:
        with self._lock:
            replica_id = self._next_id
            self._next_id += 1
            cluster = f'skyt-serve-{self.service_name}-{replica_id}'
            info = ReplicaInfo(replica_id, cluster,
                               self._replica_port(replica_id),
                               is_spot=(self.spec.use_spot
                                        if use_spot is None else use_spot),
                               version=self.version)
            self.replicas[replica_id] = info
        state.upsert_replica(self.service_name, replica_id, cluster,
                             state.ReplicaStatus.PROVISIONING, None,
                             version=info.version)
        t = threading.Thread(target=self._launch_replica, args=(info,),
                             daemon=True)
        t.start()
        self._threads.append(t)

    def _launch_replica(self, info: ReplicaInfo) -> None:
        replica_task = task_lib.Task(
            name=f'{self.service_name}-r{info.replica_id}',
            run=self.task.run, setup=self.task.setup,
            envs={**self.task.envs,
                  'SKYT_REPLICA_PORT': str(info.port),
                  'SKYT_REPLICA_ID': str(info.replica_id)},
            workdir=self.task.workdir,
            file_mounts=dict(self.task.file_mounts),
            storage_mounts=dict(self.task.storage_mounts),
        )
        # The replica must be reachable from the LB: its serving port
        # rides the resources so the provisioner opens it
        # (provision/gcp/instance.py:149 -> open_ports; VERDICT r2 #4 —
        # replicas carried no ports and were firewalled on real VPCs).
        replica_task.resources = self.task.resources.copy(
            use_spot=info.is_spot,
            ports=tuple(sorted({*self.task.resources.ports, info.port})))
        try:
            _, handle = execution.launch(replica_task,
                                         cluster_name=info.cluster_name,
                                         detach_run=True,
                                         quiet_optimizer=True)
            head = handle.cluster_info.head_instance
            ip = head.external_ip or head.internal_ip
            info.endpoint = f'{ip}:{info.port}'
            info.status = state.ReplicaStatus.STARTING
        except exceptions.SkyTpuError as e:
            logger.warning(f'replica {info.replica_id} launch failed: {e}')
            info.status = state.ReplicaStatus.FAILED
        state.upsert_replica(self.service_name, info.replica_id,
                             info.cluster_name, info.status, info.endpoint,
                             version=info.version)

    def scale_down(self, replica_id: int) -> None:
        with self._lock:
            info = self.replicas.pop(replica_id, None)
        if info is None:
            return
        info.status = state.ReplicaStatus.SHUTTING_DOWN
        state.upsert_replica(self.service_name, replica_id,
                             info.cluster_name, info.status, info.endpoint,
                             version=info.version)
        t = threading.Thread(target=self._terminate_replica, args=(info,),
                             daemon=True)
        t.start()
        self._threads.append(t)

    def _terminate_replica(self, info: ReplicaInfo) -> None:
        from skypilot_tpu import core
        try:
            core.down(info.cluster_name)
        except exceptions.ClusterDoesNotExist:
            pass
        state.remove_replica(self.service_name, info.replica_id)

    def terminate_all(self) -> None:
        with self._lock:
            ids = list(self.replicas)
        for rid in ids:
            self.scale_down(rid)
        for t in self._threads:
            t.join(timeout=60)

    # -------------------------------------------------------------- #

    def probe_all(self) -> None:
        """One readiness sweep (reference: _replica_prober :1026-1130)."""
        for info in list(self.replicas.values()):
            if info.status not in (state.ReplicaStatus.STARTING,
                                   state.ReplicaStatus.READY,
                                   state.ReplicaStatus.NOT_READY):
                continue
            if info.endpoint is None:
                continue
            in_grace = (time.time() - info.launched_at <
                        self.spec.initial_delay_seconds)
            ok = self._probe_one(info)
            if ok:
                info.consecutive_failures = 0
                if info.status != state.ReplicaStatus.READY:
                    logger.info(f'replica {info.replica_id} READY at '
                                f'{info.endpoint}')
                info.status = state.ReplicaStatus.READY
            elif not in_grace:
                info.consecutive_failures += 1
                if info.consecutive_failures >= PROBE_FAILURE_THRESHOLD:
                    logger.warning(
                        f'replica {info.replica_id} failed '
                        f'{info.consecutive_failures} probes; replacing.')
                    self.scale_down(info.replica_id)
                    self.scale_up()
                    continue
                if info.status == state.ReplicaStatus.READY:
                    info.status = state.ReplicaStatus.NOT_READY
            state.upsert_replica(self.service_name, info.replica_id,
                                 info.cluster_name, info.status,
                                 info.endpoint)

    def _probe_one(self, info: ReplicaInfo) -> bool:
        url = f'http://{info.endpoint}{self.spec.readiness_path}'
        try:
            data = (self.spec.post_data.encode()
                    if self.spec.post_data else None)
            req = urllib.request.Request(url, data=data)
            with urllib.request.urlopen(
                    req, timeout=self.spec.readiness_timeout_seconds) as r:
                return 200 <= r.status < 300
        except Exception:  # noqa: BLE001 — any failure is "not ready"
            return False

    def ready_replicas(self) -> List[ReplicaInfo]:
        return [i for i in self.replicas.values()
                if i.status == state.ReplicaStatus.READY]

    @property
    def num_alive(self) -> int:
        return len(self._alive())

    def _alive(self, *, is_spot: Optional[bool] = None
               ) -> List[ReplicaInfo]:
        out = [i for i in self.replicas.values()
               if i.status != state.ReplicaStatus.FAILED]
        if is_spot is not None:
            out = [i for i in out if i.is_spot == is_spot]
        return out

    def num_ready_spot(self) -> int:
        return len([i for i in self.ready_replicas() if i.is_spot])

    def reconcile(self, decision) -> None:
        """Converge replica counts to the decision. Mixed decisions
        (target_spot/target_ondemand) reconcile each pool; homogeneous
        ones reconcile the total."""
        if decision.target_spot is None:
            self._reconcile_pool(None, decision.target_num_replicas)
        else:
            self._reconcile_pool(True, decision.target_spot)
            self._reconcile_pool(False, decision.target_ondemand)

    def _reconcile_pool(self, is_spot: Optional[bool],
                        target: int) -> None:
        alive = self._alive(is_spot=is_spot)
        if len(alive) < target:
            for _ in range(target - len(alive)):
                self.scale_up(use_spot=is_spot)
        elif len(alive) > target:
            # Shed not-ready first, then the newest READY replicas —
            # keep the oldest, warmed ones.
            candidates = sorted(
                alive,
                key=lambda i: (i.status == state.ReplicaStatus.READY,
                               -i.replica_id))
            for info in candidates[:len(alive) - target]:
                self.scale_down(info.replica_id)

    def rollout_tick(self, decision) -> None:
        """Blue-green step for `serve update`: keep old-version replicas
        serving until the new version reaches the target ready count,
        then drain the old ones. Honors the autoscaler's spot/on-demand
        split so a fallback service's on-demand safety net is re-created
        on-demand, not as spot."""
        target = decision.target_num_replicas
        # Drain FAILED old-version replicas immediately: _alive() excludes
        # them, so without this they would sit in self.replicas forever,
        # `updating` would never go False, and the autoscaler's reconcile
        # path would be permanently disabled after the update.
        for info in list(self.replicas.values()):
            if (info.version < self.version
                    and info.status == state.ReplicaStatus.FAILED):
                self.scale_down(info.replica_id)
        new = [i for i in self._alive() if i.version == self.version]
        old = [i for i in self._alive() if i.version < self.version]
        if len(new) < target:
            if decision.target_spot is None:
                for _ in range(target - len(new)):
                    self.scale_up()
            else:
                new_spot = len([i for i in new if i.is_spot])
                new_od = len(new) - new_spot
                for _ in range(max(0, decision.target_spot - new_spot)):
                    self.scale_up(use_spot=True)
                for _ in range(max(0, decision.target_ondemand - new_od)):
                    self.scale_up(use_spot=False)
            return
        ready_new = [i for i in new
                     if i.status == state.ReplicaStatus.READY]
        if len(ready_new) >= max(1, target):
            for info in old:
                self.scale_down(info.replica_id)
