"""Serve state DB (reference: sky/serve/serve_state.py)."""
from __future__ import annotations

import enum
import json
import sqlite3
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import config as config_lib


class ServiceStatus(enum.Enum):
    CONTROLLER_INIT = 'CONTROLLER_INIT'
    REPLICA_INIT = 'REPLICA_INIT'
    READY = 'READY'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'


class ReplicaStatus(enum.Enum):
    PROVISIONING = 'PROVISIONING'
    STARTING = 'STARTING'
    READY = 'READY'
    NOT_READY = 'NOT_READY'
    SHUTTING_DOWN = 'SHUTTING_DOWN'
    FAILED = 'FAILED'
    PREEMPTED = 'PREEMPTED'


def _conn() -> sqlite3.Connection:
    conn = sqlite3.connect(str(config_lib.home_dir() / 'serve.db'),
                           timeout=30)
    conn.executescript("""
        CREATE TABLE IF NOT EXISTS services (
            name TEXT PRIMARY KEY,
            status TEXT,
            controller_pid INTEGER,
            endpoint TEXT,
            spec_json TEXT,
            created_at REAL,
            version INTEGER DEFAULT 1,
            task_yaml TEXT);
        CREATE TABLE IF NOT EXISTS replicas (
            service_name TEXT,
            replica_id INTEGER,
            cluster_name TEXT,
            status TEXT,
            endpoint TEXT,
            version INTEGER DEFAULT 1,
            PRIMARY KEY (service_name, replica_id));
    """)
    # Backfill columns for DBs created before they existed (mirrors
    # jobs/state.py): CREATE TABLE IF NOT EXISTS does not alter an
    # existing table.
    for ddl in ('ALTER TABLE services ADD COLUMN version INTEGER DEFAULT 1',
                'ALTER TABLE services ADD COLUMN task_yaml TEXT',
                'ALTER TABLE replicas ADD COLUMN version INTEGER DEFAULT 1'):
        try:
            conn.execute(ddl)
        except sqlite3.OperationalError:
            pass  # Column already exists.
    return conn


def add_service(name: str, spec_json: str,
                task_yaml: Optional[str] = None) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO services (name, status,'
            ' controller_pid, endpoint, spec_json, created_at,'
            ' version, task_yaml) VALUES (?,?,?,?,?,?,1,?)',
            (name, ServiceStatus.CONTROLLER_INIT.value, None, None,
             spec_json, time.time(), task_yaml))


def bump_version(name: str, spec_json: str, task_yaml: str) -> int:
    """`serve update`: record the new task/spec; returns new version."""
    with _conn() as conn:
        conn.execute(
            'UPDATE services SET version=version+1, spec_json=?, '
            'task_yaml=? WHERE name=?', (spec_json, task_yaml, name))
        row = conn.execute('SELECT version FROM services WHERE name=?',
                           (name,)).fetchone()
        return row[0]


def set_service(name: str, *, status: Optional[ServiceStatus] = None,
                controller_pid: Optional[int] = None,
                endpoint: Optional[str] = None) -> None:
    with _conn() as conn:
        if status is not None:
            conn.execute('UPDATE services SET status=? WHERE name=?',
                         (status.value, name))
        if controller_pid is not None:
            conn.execute('UPDATE services SET controller_pid=? '
                         'WHERE name=?', (controller_pid, name))
        if endpoint is not None:
            conn.execute('UPDATE services SET endpoint=? WHERE name=?',
                         (endpoint, name))


def get_service(name: str) -> Optional[Dict[str, Any]]:
    row = _conn().execute(
        'SELECT name, status, controller_pid, endpoint, spec_json,'
        ' created_at, version, task_yaml FROM services WHERE name=?',
        (name,)).fetchone()
    if row is None:
        return None
    return {'name': row[0], 'status': row[1], 'controller_pid': row[2],
            'endpoint': row[3], 'spec': json.loads(row[4]),
            'created_at': row[5], 'version': row[6], 'task_yaml': row[7]}


def get_services() -> List[Dict[str, Any]]:
    rows = _conn().execute('SELECT name FROM services').fetchall()
    return [get_service(r[0]) for r in rows]


def remove_service(name: str) -> None:
    with _conn() as conn:
        conn.execute('DELETE FROM services WHERE name=?', (name,))
        conn.execute('DELETE FROM replicas WHERE service_name=?', (name,))


def upsert_replica(service: str, replica_id: int, cluster_name: str,
                   status: ReplicaStatus,
                   endpoint: Optional[str], version: int = 1) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO replicas (service_name, replica_id,'
            ' cluster_name, status, endpoint, version) VALUES '
            '(?,?,?,?,?,?)',
            (service, replica_id, cluster_name, status.value, endpoint,
             version))


def remove_replica(service: str, replica_id: int) -> None:
    with _conn() as conn:
        conn.execute('DELETE FROM replicas WHERE service_name=? AND '
                     'replica_id=?', (service, replica_id))


def get_replicas(service: str) -> List[Dict[str, Any]]:
    rows = _conn().execute(
        'SELECT replica_id, cluster_name, status, endpoint, version '
        'FROM replicas WHERE service_name=? ORDER BY replica_id',
        (service,)).fetchall()
    return [{'replica_id': r[0], 'cluster_name': r[1], 'status': r[2],
             'endpoint': r[3], 'version': r[4] or 1} for r in rows]
