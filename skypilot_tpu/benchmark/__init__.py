"""Benchmark subsystem (reference: sky/benchmark/ — fan-out candidate
launches + sec/step & $/step reporting from step-callback logs)."""
from skypilot_tpu.benchmark.utils import (delete_benchmark,
                                          format_report, launch_benchmark,
                                          teardown_benchmark,
                                          update_benchmark)

__all__ = ['launch_benchmark', 'update_benchmark', 'format_report',
           'teardown_benchmark', 'delete_benchmark']
