"""Benchmark orchestration: fan-out launch, step-log harvest, report.

Reference: sky/benchmark/benchmark_utils.py (892 LoC) — `sky bench
launch` generates one candidate config per resource option, launches all
in parallel, and `sky bench show` pulls the callback's timestamped step
logs to compute sec/step and $/step (the BERT table in
docs/source/reference/benchmark/index.rst is its output). Differences
here: candidates are TPU topologies (v5e-8 vs v6e-8 vs v4-8 ...), logs
come back over the CommandRunner (rsync) instead of a cloud bucket, and
$/step uses the catalog's TPU pricing directly.
"""
from __future__ import annotations

import json
import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import callbacks
from skypilot_tpu import exceptions
from skypilot_tpu import execution
from skypilot_tpu import global_user_state
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib
from skypilot_tpu.backend import CloudTpuBackend
from skypilot_tpu.benchmark import state
from skypilot_tpu.utils import subprocess_utils

logger = sky_logging.init_logger(__name__)

_REMOTE_LOG_DIR = '~/.skyt/benchmark_logs'


def cluster_name(benchmark: str, idx: int) -> str:
    return f'skyt-bench-{benchmark}-{idx}'


def launch_benchmark(task: task_lib.Task,
                     benchmark: str,
                     candidates: List[Dict[str, Any]],
                     parallel: int = 4) -> List[str]:
    """Launch one cluster per candidate resource override, in parallel.

    candidates: list of Resources.copy(**overrides) dicts, e.g.
    [{'accelerators': 'tpu-v5e-8'}, {'accelerators': 'tpu-v6e-8'}].
    Returns the launched cluster names. Each job gets
    SKYT_BENCHMARK_LOG_DIR pointed at a per-benchmark path that
    `update_benchmark` later pulls.
    """
    state.add_benchmark(benchmark, task.name or '-')
    names = []

    def _launch_one(args):
        idx, overrides = args
        name = cluster_name(benchmark, idx)
        res = task.resources.copy(**overrides)
        bench_task = task_lib.Task(
            name=f'{task.name or "bench"}-{idx}',
            run=task.run, setup=task.setup, num_nodes=task.num_nodes,
            workdir=task.workdir, file_mounts=task.file_mounts,
            storage_mounts=task.storage_mounts,
            envs={**(task.envs or {}),
                  callbacks.ENV_LOG_DIR: f'{_REMOTE_LOG_DIR}/{benchmark}'})
        bench_task.set_resources(res)
        state.add_result(benchmark, name, str(res), res.hourly_price(),
                         'LAUNCHING')
        try:
            job_id, _ = execution.launch(bench_task, cluster_name=name,
                                         detach_run=True,
                                         quiet_optimizer=True)
            state.update_result(benchmark, name, status='RUNNING',
                                job_id=job_id)
        except Exception as e:  # noqa: BLE001 — candidate may be infeasible
            logger.warning(f'benchmark candidate {name} failed: {e}')
            state.update_result(benchmark, name, status='FAILED')
        return name

    names = subprocess_utils.run_in_parallel(
        _launch_one, list(enumerate(candidates)), parallel)
    return list(names)


def _parse_step_logs(local_dir: str) -> Optional[Dict[str, Any]]:
    ts_path = os.path.join(local_dir, 'timestamps.jsonl')
    if not os.path.exists(ts_path):
        return None
    steps = []
    with open(ts_path) as f:
        for line in f:
            line = line.strip()
            if line:
                steps.append(json.loads(line))
    if len(steps) < 2:
        return None
    cfg_path = os.path.join(local_dir, 'config.json')
    total = None
    if os.path.exists(cfg_path):
        with open(cfg_path) as f:
            total = json.load(f).get('total_steps')
    # First interval includes jit compile; drop it when we can afford to
    # (reference discards warmup the same way via its boot timestamp).
    ts = [s['ts'] for s in steps]
    intervals = [b - a for a, b in zip(ts, ts[1:])]
    if len(intervals) > 2:
        intervals = intervals[1:]
    sec_per_step = sum(intervals) / len(intervals)
    return {'num_steps': len(steps), 'seconds_per_step': sec_per_step,
            'total_steps': total, 'start_ts': ts[0], 'last_ts': ts[-1]}


def update_benchmark(benchmark: str) -> List[Dict[str, Any]]:
    """Pull step logs from every candidate cluster (in parallel) and
    recompute sec/step + $/step. Returns the refreshed result rows."""
    backend = CloudTpuBackend()

    def _refresh(row):
        record = global_user_state.get_cluster(row['cluster'])
        if record is None or record['handle'] is None:
            if row['status'] not in ('FAILED', 'TERMINATED'):
                state.update_result(benchmark, row['cluster'],
                                    status='TERMINATED')
            return
        handle = record['handle']
        # Stable per-(benchmark, cluster) dir under SKYT_HOME: repeated
        # `bench show` calls overwrite instead of leaking tempdirs.
        home = os.path.expanduser(os.environ.get('SKYT_HOME', '~/.skyt'))
        local_dir = os.path.join(home, 'benchmark_logs', benchmark,
                                 row['cluster'])
        os.makedirs(local_dir, exist_ok=True)
        try:
            handle.head_runner().rsync(f'{_REMOTE_LOG_DIR}/{benchmark}/',
                                       local_dir, up=False, check=False)
        except Exception as e:  # noqa: BLE001
            logger.debug(f'log pull failed for {row["cluster"]}: {e}')
            return
        parsed = _parse_step_logs(local_dir)
        if parsed is None:
            return
        price = row['hourly_price']
        cost = (price / 3600.0 * parsed['seconds_per_step']
                if price else None)
        status = row['status']
        # No recorded job id → leave status unchanged rather than guessing
        # job 1 (which may be an unrelated job on a reused cluster).
        if row['job_id'] is not None:
            job_status = backend.get_job_status(handle, row['job_id'])
            if job_status in ('SUCCEEDED', 'FAILED', 'CANCELLED'):
                status = ('FINISHED' if job_status == 'SUCCEEDED'
                          else job_status)
        state.update_result(
            benchmark, row['cluster'], status=status,
            num_steps=parsed['num_steps'],
            seconds_per_step=parsed['seconds_per_step'],
            cost_per_step=cost, total_steps=parsed['total_steps'],
            start_ts=parsed['start_ts'], last_ts=parsed['last_ts'])

    subprocess_utils.run_in_parallel(_refresh, state.get_results(benchmark),
                                     8)
    return state.get_results(benchmark)


def format_report(benchmark: str) -> str:
    rows = state.get_results(benchmark)
    header = ['CLUSTER', 'RESOURCES', 'STATUS', 'STEPS', 'SEC/STEP',
              '$/STEP', '$/HR']
    lines = ['  '.join(f'{h:<18}' for h in header)]
    for r in rows:
        sps = (f"{r['seconds_per_step']:.4f}"
               if r['seconds_per_step'] else '-')
        cps = f"{r['cost_per_step']:.6f}" if r['cost_per_step'] else '-'
        price = f"{r['hourly_price']:.2f}" if r['hourly_price'] else '-'
        cells = [r['cluster'], r['resources'][:18], r['status'] or '-',
                 str(r['num_steps'] or 0), sps, cps, price]
        lines.append('  '.join(f'{c:<18}' for c in cells))
    return '\n'.join(lines)


def teardown_benchmark(benchmark: str) -> None:
    """`sky bench down`: terminate every candidate cluster."""
    backend = CloudTpuBackend()

    def _down(row):
        record = global_user_state.get_cluster(row['cluster'])
        if record is not None and record['handle'] is not None:
            try:
                backend.teardown(record['handle'])
            except Exception as e:  # noqa: BLE001
                # Leave the row as-is: a cluster we failed to tear down
                # is still running (and billing) — hiding it behind
                # TERMINATED would orphan it.
                logger.warning(f'teardown {row["cluster"]} failed, '
                               f'still tracked: {e}')
                return
        state.update_result(benchmark, row['cluster'],
                            status='TERMINATED')

    subprocess_utils.run_in_parallel(_down, state.get_results(benchmark), 4)


def delete_benchmark(benchmark: str, force: bool = False) -> None:
    """Drop tracking rows. Refuses while candidate clusters may still be
    running (they would become undiscoverable) unless force=True."""
    live = [r['cluster'] for r in state.get_results(benchmark)
            if r['status'] not in ('TERMINATED', 'FAILED', None)]
    if live and not force:
        raise exceptions.NotSupportedError(
            f'Benchmark {benchmark!r} has non-terminated clusters '
            f'({", ".join(live)}); run `skyt bench down {benchmark}` '
            'first or pass --force.')
    state.delete_benchmark(benchmark)
