"""Benchmark state DB (reference: sky/benchmark/benchmark_state.py —
SQLite at ~/.sky/benchmark.db with benchmark + benchmark_results tables)."""
from __future__ import annotations

import json
import os
import sqlite3
import time
from typing import Any, Dict, List, Optional


def _db_path() -> str:
    home = os.path.expanduser(os.environ.get('SKYT_HOME', '~/.skyt'))
    os.makedirs(home, exist_ok=True)
    return os.path.join(home, 'benchmark.db')


def _conn() -> sqlite3.Connection:
    conn = sqlite3.connect(_db_path())
    conn.execute("""
        CREATE TABLE IF NOT EXISTS benchmark (
            name TEXT PRIMARY KEY,
            task_name TEXT,
            launched_at REAL)""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS benchmark_results (
            benchmark TEXT,
            cluster TEXT,
            resources TEXT,
            hourly_price REAL,
            status TEXT,
            job_id INTEGER,
            num_steps INTEGER DEFAULT 0,
            seconds_per_step REAL,
            cost_per_step REAL,
            total_steps INTEGER,
            start_ts REAL,
            last_ts REAL,
            PRIMARY KEY (benchmark, cluster))""")
    return conn


def add_benchmark(name: str, task_name: str) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO benchmark VALUES (?, ?, ?)',
            (name, task_name, time.time()))


def add_result(benchmark: str, cluster: str, resources: str,
               hourly_price: Optional[float], status: str) -> None:
    with _conn() as conn:
        conn.execute(
            'INSERT OR REPLACE INTO benchmark_results '
            '(benchmark, cluster, resources, hourly_price, status) '
            'VALUES (?, ?, ?, ?, ?)',
            (benchmark, cluster, resources, hourly_price, status))


def update_result(benchmark: str, cluster: str, *,
                  status: Optional[str] = None,
                  job_id: Optional[int] = None,
                  num_steps: Optional[int] = None,
                  seconds_per_step: Optional[float] = None,
                  cost_per_step: Optional[float] = None,
                  total_steps: Optional[int] = None,
                  start_ts: Optional[float] = None,
                  last_ts: Optional[float] = None) -> None:
    sets, vals = [], []
    for col, val in [('status', status), ('job_id', job_id),
                     ('num_steps', num_steps),
                     ('seconds_per_step', seconds_per_step),
                     ('cost_per_step', cost_per_step),
                     ('total_steps', total_steps),
                     ('start_ts', start_ts), ('last_ts', last_ts)]:
        if val is not None:
            sets.append(f'{col} = ?')
            vals.append(val)
    if not sets:
        return
    with _conn() as conn:
        conn.execute(
            f'UPDATE benchmark_results SET {", ".join(sets)} '
            'WHERE benchmark = ? AND cluster = ?',
            (*vals, benchmark, cluster))


def get_benchmarks() -> List[Dict[str, Any]]:
    with _conn() as conn:
        rows = conn.execute('SELECT name, task_name, launched_at '
                            'FROM benchmark').fetchall()
    return [{'name': r[0], 'task_name': r[1], 'launched_at': r[2]}
            for r in rows]


def get_results(benchmark: str) -> List[Dict[str, Any]]:
    cols = ['benchmark', 'cluster', 'resources', 'hourly_price', 'status',
            'job_id', 'num_steps', 'seconds_per_step', 'cost_per_step',
            'total_steps', 'start_ts', 'last_ts']
    with _conn() as conn:
        rows = conn.execute(
            f'SELECT {", ".join(cols)} FROM benchmark_results '
            'WHERE benchmark = ?', (benchmark,)).fetchall()
    return [dict(zip(cols, r)) for r in rows]


def delete_benchmark(name: str) -> None:
    with _conn() as conn:
        conn.execute('DELETE FROM benchmark WHERE name = ?', (name,))
        conn.execute('DELETE FROM benchmark_results WHERE benchmark = ?',
                     (name,))


def dumps_resources(overrides: Dict[str, Any]) -> str:
    return json.dumps(overrides, sort_keys=True)
