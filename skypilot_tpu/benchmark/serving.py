"""Request-level online serving benchmark (client harness).

The reference's headline serving number is REQUEST-level: JetStream's
benchmark script drives 100 concurrent HTTP requests through the model
server and reports req/s and output tok/s (reference
examples/tpu/v6e/README.md:110-120 — 11.42 req/s, 2148 output tok/s,
8.75 s wallclock). This module is the in-framework equivalent for
`serve.engine_server`: N concurrent clients stream `/v1/completions`
(SSE) and the harness reports req/s, output tok/s, time-to-first-token
and inter-token latency percentiles — the numbers online serving is
actually judged by, which the offline `generate_batch` path cannot see
(per-step host sync, slot refill, prefill/decode interleaving all only
exist in the online loop).

Pure stdlib client (http.client + threads): the harness must not need
the server's own event loop, and it runs anywhere the CPU-tier tests
do.
"""
from __future__ import annotations

import dataclasses
import http.client
import json
import threading
import time
from typing import Any, Dict, List, Optional, Sequence


@dataclasses.dataclass
class RequestResult:
    ok: bool
    start_s: float
    end_s: float
    n_tokens: int = 0
    ttft_s: Optional[float] = None
    itl_s: List[float] = dataclasses.field(default_factory=list)
    error: Optional[str] = None


def _percentile(xs: Sequence[float], p: float) -> Optional[float]:
    """Nearest-rank percentile, rounded; None on empty input (NaN is
    not valid strict JSON, and the BENCH artifact must stay
    machine-readable). No numpy: the client harness stays
    dependency-free."""
    if not xs:
        return None
    s = sorted(xs)
    idx = min(len(s) - 1, max(0, int(round(p / 100.0 * (len(s) - 1)))))
    return round(s[idx], 4)


def _stream_one(host: str, port: int, payload: Dict[str, Any],
                timeout_s: float) -> RequestResult:
    """POST /v1/completions with stream=true; timestamp every SSE data
    frame as it arrives off the socket. TTFT/ITL come from the text
    frames (what a streaming client observes); the token COUNT comes
    from the final stream_options.include_usage chunk — text deltas do
    not map 1:1 to tokens (a multi-byte token can buffer in the
    incremental decoder and emit nothing)."""
    t0 = time.perf_counter()
    res = RequestResult(ok=False, start_s=t0, end_s=t0)
    conn = http.client.HTTPConnection(host, port, timeout=timeout_s)
    try:
        body = json.dumps({**payload, 'stream': True,
                           'stream_options': {'include_usage': True}})
        conn.request('POST', '/v1/completions', body=body,
                     headers={'Content-Type': 'application/json'})
        resp = conn.getresponse()
        if resp.status != 200:
            res.error = f'HTTP {resp.status}: {resp.read()[:200]!r}'
            res.end_s = time.perf_counter()
            return res
        last_tok_t = None
        buf = b''
        while True:
            chunk = resp.read1(65536)
            if not chunk:
                break
            now = time.perf_counter()
            first_text_in_chunk = True
            buf += chunk
            while b'\n' in buf:
                line, buf = buf.split(b'\n', 1)
                line = line.strip()
                if not line.startswith(b'data:'):
                    continue
                data = line[len(b'data:'):].strip()
                if data == b'[DONE]':
                    continue
                try:
                    frame = json.loads(data)
                except json.JSONDecodeError:
                    continue
                if 'error' in frame:
                    # In-band rejection (SSE headers already sent, so
                    # the server can only report errors as frames).
                    res.error = str(frame['error'])[:200]
                    continue
                if 'usage' in frame and not frame.get('choices'):
                    res.n_tokens = int(
                        frame['usage']['completion_tokens'])
                    continue
                choices = frame.get('choices') or []
                # A text frame marks observable progress; the final
                # finish_reason-only frame is not one. Frames sharing
                # one socket read arrived together (TCP coalescing):
                # they are ONE latency observation, not a burst of
                # zero-length intervals that would deflate the ITL
                # percentiles.
                if choices and choices[0].get('text', '') != '':
                    if res.ttft_s is None:
                        res.ttft_s = now - t0
                    elif (last_tok_t is not None
                          and first_text_in_chunk):
                        res.itl_s.append(now - last_tok_t)
                    first_text_in_chunk = False
                    last_tok_t = now
        res.ok = res.n_tokens > 0
        if not res.ok:
            res.error = res.error or 'stream produced no tokens'
    except Exception as e:  # noqa: BLE001 — recorded per-request
        res.error = f'{type(e).__name__}: {e}'
    finally:
        conn.close()
        res.end_s = time.perf_counter()
    return res


def run_benchmark(host: str, port: int,
                  prompts: Sequence[Any],
                  max_tokens: int = 64,
                  concurrency: int = 16,
                  timeout_s: float = 300.0,
                  extra: Optional[Dict[str, Any]] = None
                  ) -> Dict[str, Any]:
    """Drive every prompt through the server with at most `concurrency`
    in flight; returns the metrics block (all latencies in seconds).
    `prompts` entries are passed as the OpenAI `prompt` field (str or
    token-id list)."""
    results: List[Optional[RequestResult]] = [None] * len(prompts)
    sem = threading.Semaphore(concurrency)

    def worker(i: int, prompt: Any) -> None:
        with sem:
            payload = {'prompt': prompt, 'max_tokens': max_tokens,
                       **(extra or {})}
            results[i] = _stream_one(host, port, payload, timeout_s)

    t0 = time.perf_counter()
    threads = [threading.Thread(target=worker, args=(i, p), daemon=True)
               for i, p in enumerate(prompts)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=timeout_s + 60)
    wall = time.perf_counter() - t0

    done = [r for r in results if r is not None]
    ok = [r for r in done if r.ok]
    total_tokens = sum(r.n_tokens for r in ok)
    ttfts = [r.ttft_s for r in ok if r.ttft_s is not None]
    itls = [x for r in ok for x in r.itl_s]
    lats = [r.end_s - r.start_s for r in ok]
    report: Dict[str, Any] = {
        'num_requests': len(prompts),
        'num_ok': len(ok),
        'concurrency': concurrency,
        'max_tokens': max_tokens,
        'wall_s': round(wall, 3),
        'req_per_s': round(len(ok) / wall, 2) if wall > 0 else 0.0,
        'output_tok_per_s': round(total_tokens / wall, 1)
        if wall > 0 else 0.0,
        'total_output_tokens': total_tokens,
        'ttft_p50_s': _percentile(ttfts, 50),
        'ttft_p99_s': _percentile(ttfts, 99),
        'itl_p50_s': _percentile(itls, 50),
        'itl_p99_s': _percentile(itls, 99),
        'latency_p50_s': _percentile(lats, 50),
        'latency_p99_s': _percentile(lats, 99),
    }
    errors = [r.error for r in done if not r.ok and r.error]
    if errors:
        report['errors'] = errors[:5]
    if len(ok) != len(prompts):
        report['num_failed'] = len(prompts) - len(ok)
    return report
