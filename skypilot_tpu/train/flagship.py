"""Flagship-config proof: Llama-3-8B FSDP on a v5p-64, ahead of time.

BASELINE.md's north star is Llama-3-8B at >=40% MFU on an
auto-provisioned v5p-64 (reference recipe it replaces:
examples/tpu/v6e/train-llama3-8b.yaml:44-52, HF run_clm + torch-xla
FSDP). Real v5p-64 hardware is not attached in CI, so this module proves
everything that can be proven without it:

  * the FULL 8B train step (fwd+bwd+adamw, remat, bf16) LOWERS AND
    COMPILES for the v5p-64 device count (32 chips) with the real FSDP
    shardings — on a 32-device virtual CPU mesh, exercising the exact
    partitioning XLA will use on the pod;
  * XLA's own `compiled.memory_analysis()` proves the per-chip HBM
    fits the v5p's 95 GB — arguments (params + opt state + batch),
    temps (activations, logits, attention workspace) and outputs are
    all accounted by the compiler, not by hand;
  * the hand HBM estimate (feasibility.check_hbm) is validated against
    the compiler's number so the optimizer's feasibility gate stays
    honest.

Run directly (spawned as a subprocess by tests/test_flagship.py and by
__graft_entry__.dryrun_multichip's 8B-geometry stage):

    XLA_FLAGS=--xla_force_host_platform_device_count=32 \
        python -m skypilot_tpu.train.flagship

Attention note: on the CPU mesh the Pallas TPU flash kernel cannot
lower, so the compile check uses the dense-attention path; the TPU
runtime path dispatches to ops/flash_attention.py, whose memory is
strictly smaller (no [S, S] scores materialization), so the CPU
memory_analysis is an UPPER bound on the TPU footprint.
"""
from __future__ import annotations

import dataclasses
import functools
import json
from typing import Any, Dict

FLAGSHIP_TPU = 'v5p-64'          # 32 chips / 8 hosts, 95 GB HBM per chip
FLAGSHIP_SEQ = 8192
FLAGSHIP_GLOBAL_BATCH = 32       # one 8k sequence per chip


def flagship_config(use_flash_attention: bool):
    from skypilot_tpu.models import llama
    return dataclasses.replace(llama.llama3_8b(),
                               use_flash_attention=use_flash_attention)


def flagship_footprint() -> Any:
    from skypilot_tpu import feasibility
    return feasibility.TrainFootprint.from_llama_config(
        flagship_config(True), global_batch=FLAGSHIP_GLOBAL_BATCH,
        seq_len=FLAGSHIP_SEQ)


def aot_compile_flagship(backend_is_cpu: bool = True) -> Dict[str, Any]:
    """Lower + compile the full train step for 32 devices; return the
    compiler's per-device memory analysis plus the hand estimate."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu import feasibility, tpu_topology
    from skypilot_tpu.models import llama
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.train import trainer

    topo = tpu_topology.parse_tpu_type(FLAGSHIP_TPU)
    n = topo.num_chips
    devices = jax.devices()
    if len(devices) < n:
        raise RuntimeError(
            f'need {n} devices for the {FLAGSHIP_TPU} mesh, have '
            f'{len(devices)} — run under '
            f'XLA_FLAGS=--xla_force_host_platform_device_count={n}')

    cfg = flagship_config(use_flash_attention=not backend_is_cpu)
    # Pure FSDP over all 32 chips — the BASELINE "JAX FSDP variant" of
    # the reference's --fsdp full_shard recipe.
    mesh = mesh_lib.make_mesh(mesh_lib.MeshShape(fsdp=n),
                              devices=devices[:n])

    optimizer = trainer.default_optimizer()
    params_struct = jax.eval_shape(
        functools.partial(llama.init_params, cfg=cfg),
        jax.random.PRNGKey(0))
    opt_struct = jax.eval_shape(optimizer.init, params_struct)
    shardings = trainer.state_shardings(cfg, mesh, params_struct,
                                        opt_struct)
    state_struct = trainer.TrainState(
        step=jax.ShapeDtypeStruct((), jnp.int32),
        params=params_struct, opt_state=opt_struct)
    batch_struct = {'tokens': jax.ShapeDtypeStruct(
        (FLAGSHIP_GLOBAL_BATCH, FLAGSHIP_SEQ + 1), jnp.int32)}

    step = trainer.make_train_step(cfg, mesh, optimizer, shardings)
    lowered = step.lower(state_struct, batch_struct)
    compiled = lowered.compile()
    mem = compiled.memory_analysis()

    gib = 1024 ** 3
    # Semantics probed empirically (see tests/test_flagship.py): on the
    # host-platform CPU backend all N partitions live in ONE executable,
    # and argument_size is already per-device (scales 1/N) while
    # temp_size aggregates across the local partitions (invariant in N
    # at fixed global problem, linear in global batch) — so per-chip
    # temps are temp_size / N.
    arg_gb = mem.argument_size_in_bytes / gib
    out_gb = mem.output_size_in_bytes / gib
    tmp_gb = mem.temp_size_in_bytes / gib / n
    # Donation aliases state args onto outputs; peak is args + temps.
    peak_gb = arg_gb + tmp_gb

    est = feasibility.check_hbm(flagship_footprint(), topo)
    return {
        'config': 'llama3-8b',
        'params_b': round(cfg.num_params / 1e9, 3),
        'topology': FLAGSHIP_TPU,
        'mesh': {'fsdp': n},
        'seq_len': FLAGSHIP_SEQ,
        'global_batch': FLAGSHIP_GLOBAL_BATCH,
        'xla_per_chip_gb': {
            'arguments': round(arg_gb, 2),
            'outputs': round(out_gb, 2),
            'temps': round(tmp_gb, 2),
            'peak': round(peak_gb, 2),
        },
        'estimate_per_chip_gb': {k: round(v, 2) for k, v in est.items()},
        'hbm_gb_per_chip': topo.info.hbm_gb_per_chip,
        'fits': peak_gb < topo.info.hbm_gb_per_chip,
    }


def main() -> None:
    import os
    os.environ.setdefault(
        'XLA_FLAGS', '--xla_force_host_platform_device_count=32')
    import jax
    try:
        jax.config.update('jax_platforms', 'cpu')
    except Exception:  # noqa: BLE001
        pass
    report = aot_compile_flagship(backend_is_cpu=True)
    print('FLAGSHIP_JSON: ' + json.dumps(report))
    assert report['fits'], (
        f'flagship config does not fit: {report}')


if __name__ == '__main__':
    main()
