"""SPMD training loop pieces: loss, train state, jitted step.

Replaces the reference's recipe-level `torchrun ... run_clm.py --fsdp
"full_shard"` (examples/tpu/v6e/train-llama3-8b.yaml:48-49) with an
in-framework jit train step: params sharded per models/llama.py
param_shardings (FSDP over 'fsdp' axis, megatron over 'tp'), batch over
('dp','fsdp'), optimizer states sharded like their params, donated
arguments so the update is in-place in HBM.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.parallel import mesh as mesh_lib


@dataclasses.dataclass
class TrainState:
    step: jax.Array
    params: llama.Params
    opt_state: Any

    def tree_flatten(self):
        return (self.step, self.params, self.opt_state), None

    @classmethod
    def tree_unflatten(cls, aux, children):
        del aux
        return cls(*children)


jax.tree_util.register_pytree_node(
    TrainState, TrainState.tree_flatten, TrainState.tree_unflatten)


def cross_entropy_loss(logits: jax.Array, targets: jax.Array,
                       mask: Optional[jax.Array] = None) -> jax.Array:
    """Mean next-token CE in fp32. logits [B,S,V], targets [B,S]."""
    logits = logits.astype(jnp.float32)
    log_probs = jax.nn.log_softmax(logits, axis=-1)
    nll = -jnp.take_along_axis(log_probs, targets[..., None],
                               axis=-1).squeeze(-1)
    if mask is None:
        return jnp.mean(nll)
    mask = mask.astype(jnp.float32)
    return jnp.sum(nll * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def default_optimizer(lr: float = 3e-4,
                      weight_decay: float = 0.1,
                      warmup_steps: int = 100,
                      total_steps: int = 10000,
                      grad_clip: float = 1.0) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        0.0, lr, warmup_steps, max(total_steps, warmup_steps + 1))
    return optax.chain(
        optax.clip_by_global_norm(grad_clip),
        optax.adamw(schedule, b1=0.9, b2=0.95, eps=1e-8,
                    weight_decay=weight_decay),
    )


def state_shardings(cfg: Any, mesh: Mesh,
                    params_struct: Any, opt_state_struct: Any,
                    model: Any = llama) -> TrainState:
    """NamedShardings for the whole TrainState. Optimizer moments (mu/nu in
    adamw) are structural copies of the param tree, so each opt-state leaf
    inherits the spec of the param whose tree path its own path ends with
    (path-suffix match — NOT shape match: wq and wo are identically shaped
    but transposed-sharded). Scalar leaves (step counts) replicate.

    `model` is any module exposing init_params/param_shardings/forward
    (models/llama.py, models/mixtral.py, ...)."""
    del params_struct
    pspecs = model.param_shardings(cfg)
    return TrainState(
        step=NamedSharding(mesh, P()),
        params=jax.tree.map(lambda s: NamedSharding(mesh, s), pspecs),
        opt_state=opt_state_shardings(mesh, pspecs, opt_state_struct))


def opt_state_shardings(mesh: Mesh, pspecs: Any,
                        opt_state_struct: Any) -> Any:
    """Shard optimizer-state leaves by PATH-SUFFIX match against the
    param spec tree (mu/nu are structural copies of the params) — not
    by shape, which collides for identically-shaped but
    transposed-sharded weights (wq vs wo). Scalars replicate. Shared
    by the full trainer and the LoRA adapter trainer."""

    def _path_key(path) -> tuple:
        out = []
        for p in path:
            key = getattr(p, 'key', None)
            out.append(str(key if key is not None else
                           getattr(p, 'idx', p)))
        return tuple(out)

    spec_by_path = {}
    for path, spec in jax.tree_util.tree_flatten_with_path(pspecs)[0]:
        spec_by_path[_path_key(path)] = spec

    def opt_leaf_sharding(path, leaf):
        del leaf
        key = _path_key(path)
        for i in range(len(key)):
            spec = spec_by_path.get(key[i:])
            if spec is not None:
                return NamedSharding(mesh, spec)
        return NamedSharding(mesh, P())

    return jax.tree_util.tree_map_with_path(opt_leaf_sharding,
                                            opt_state_struct)


def init_train_state(cfg: Any, mesh: Mesh,
                     optimizer: Optional[optax.GradientTransformation] = None,
                     seed: int = 0,
                     model: Any = llama,
                     params: Any = None
                     ) -> Tuple[TrainState, TrainState, Any]:
    """Initialize params/opt-state directly sharded on the mesh (no host
    round-trip: jit with out_shardings materializes each shard on its
    device). Returns (state, shardings, optimizer).

    `params`: existing weights to finetune from (e.g. a converted HF
    checkpoint, models/hf_convert.py — the in-framework analog of the
    reference's llm/llama-3_1-finetuning torchrun recipe). Host numpy
    leaves go straight into their sharded layout; only the optimizer
    state is initialized on-device."""
    optimizer = optimizer or default_optimizer()
    if params is not None:
        params_struct = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype), params)
    else:
        params_struct = jax.eval_shape(
            functools.partial(model.init_params, cfg=cfg),
            jax.random.PRNGKey(0))
    opt_struct = jax.eval_shape(optimizer.init, params_struct)
    shardings = state_shardings(cfg, mesh, params_struct, opt_struct,
                                model=model)

    if params is not None:
        params = jax.device_put(params, shardings.params)
        opt_state = jax.jit(
            optimizer.init, out_shardings=shardings.opt_state)(params)
        state = TrainState(
            step=jax.device_put(jnp.zeros((), jnp.int32),
                                shardings.step),
            params=params, opt_state=opt_state)
        return state, shardings, optimizer

    def _init(key):
        init = model.init_params(key, cfg)
        return TrainState(step=jnp.zeros((), jnp.int32), params=init,
                          opt_state=optimizer.init(init))

    state = jax.jit(_init, out_shardings=shardings)(
        jax.random.PRNGKey(seed))
    return state, shardings, optimizer


def make_train_step(cfg: Any, mesh: Mesh,
                    optimizer: optax.GradientTransformation,
                    shardings: TrainState,
                    model: Any = llama,
                    loss_fn: Optional[Callable] = None
                    ) -> Callable[[TrainState, Dict[str, jax.Array]],
                                  Tuple[TrainState, Dict[str, jax.Array]]]:
    """Jitted SPMD train step. batch = {'tokens': [B, S+1] int32} (inputs
    tokens[:, :-1], targets tokens[:, 1:]); donates state.

    `loss_fn(params, tokens) -> scalar` overrides the default next-token
    CE; models with auxiliary losses expose `make_loss_fn(cfg)` (e.g.
    mixtral's router load-balance loss) which is used automatically."""
    batch_sharding = NamedSharding(mesh, P(('dp', 'fsdp'), None))

    if loss_fn is None:
        if hasattr(model, 'make_loss_fn'):
            loss_fn = model.make_loss_fn(cfg)
        else:
            def loss_fn(params, tokens):
                inputs, targets = tokens[:, :-1], tokens[:, 1:]
                logits = model.forward(params, inputs, cfg)
                return cross_entropy_loss(logits, targets)

    def step_fn(state: TrainState, batch: Dict[str, jax.Array]):
        with mesh_lib.use_mesh(mesh):   # visible to ops during tracing
            loss, grads = jax.value_and_grad(loss_fn)(state.params,
                                                      batch['tokens'])
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_params = optax.apply_updates(state.params, updates)
        metrics = {'loss': loss,
                   'grad_norm': optax.global_norm(grads),
                   'step': state.step + 1}
        return TrainState(step=state.step + 1, params=new_params,
                          opt_state=new_opt), metrics

    return jax.jit(
        step_fn,
        in_shardings=(shardings, {'tokens': batch_sharding}),
        out_shardings=(shardings, None),
        donate_argnums=(0,))
