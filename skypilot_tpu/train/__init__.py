from skypilot_tpu.train.trainer import (TrainState, cross_entropy_loss,
                                        make_train_step, init_train_state)

__all__ = ['TrainState', 'cross_entropy_loss', 'make_train_step',
           'init_train_state']
