"""Checkpoint save/restore for train states (orbax-backed).

This is the piece that makes managed-spot recovery a *resume* instead of
a restart: the recipe points `--ckpt-dir` at a MOUNT-mode bucket
(examples/jobs_spot_recovery.yaml), saves every N steps, and on relaunch
restores the latest step. Reference patterns: the bucket-mounted
checkpoint dir in `llm/llama-3_1-finetuning/lora.yaml:24-58` and the
`checkpoint_dir` convention in its train recipes; the reference itself
ships no checkpoint library (orchestrator-only) — this is in-framework.

Multi-host: orbax coordinates across `jax.process_count()` processes, so
every process must call save/restore collectively (the gang executor
starts one process per host; all of them run the same recipe loop).
"""
from __future__ import annotations

import os
from typing import Any, Optional, Tuple

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


class CheckpointManager:
    """Thin wrapper over orbax CheckpointManager: step-indexed save /
    restore-latest with bounded retention, saving asynchronously so the
    train loop never blocks on bucket writes."""

    def __init__(self, directory: str, max_to_keep: int = 3) -> None:
        import orbax.checkpoint as ocp
        self._ocp = ocp
        self.directory = os.path.abspath(os.path.expanduser(directory))
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=max_to_keep, create=True,
                enable_async_checkpointing=True))

    def latest_step(self) -> Optional[int]:
        return self._mgr.latest_step()

    def save(self, step: int, state: Any) -> None:
        self._mgr.save(step, args=self._ocp.args.StandardSave(state))

    def restore_latest(self, template: Any
                       ) -> Tuple[Optional[int], Optional[Any]]:
        """Restore the newest checkpoint into `template`'s structure,
        dtypes, and shardings (pass the live, mesh-sharded train state —
        restored arrays land directly in its shardings). Returns
        (step, state) or (None, None) when the directory has no
        checkpoints yet (first launch)."""
        step = self._mgr.latest_step()
        if step is None:
            return None, None
        state = self._mgr.restore(
            step, args=self._ocp.args.StandardRestore(template))
        logger.info(f'Restored checkpoint step {step} from '
                    f'{self.directory}')
        return step, state

    def restore_latest_raw(self) -> Tuple[Optional[int], Optional[Any]]:
        """Restore the newest checkpoint WITHOUT a template, from the
        structure metadata orbax stored at save time — for consumers
        that don't know the tree up front (native serving checkpoints:
        the engine learns the param dtypes/shapes from the checkpoint,
        not the other way around). Returns (step, tree) or
        (None, None)."""
        step = self._mgr.latest_step()
        if step is None:
            return None, None
        return step, self._mgr.restore(step)

    def wait(self) -> None:
        """Block until in-flight async saves are durable (call before
        process exit, or the last save may be a torn partial)."""
        self._mgr.wait_until_finished()

    def close(self) -> None:
        self.wait()
        self._mgr.close()
