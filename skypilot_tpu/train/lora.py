"""LoRA finetuning, TPU-first (reference capability:
llm/llama-3_1-finetuning/lora.yaml — the reference shells out to
torchtune; here the adapters train in-framework on the same functional
models that serve).

Design:
  * adapters are their OWN pytree ({layer_key: {'a': [L, D, r],
    'b': [L, r, F]}}); the base model is a frozen INPUT to the train
    step (not a closure constant — XLA would bake gigabytes of weights
    into the executable), so optimizer state exists only for the
    adapters: finetuning an 8B model carries ~millions, not billions,
    of Adam moments.
  * `apply()` grafts ops/quant.LoraWeight leaves onto the param tree;
    every projection already routes through quant.qdot, which computes
    the factored x@W + ((x@A)@B)*alpha/r — no materialized deltas, and
    the base may be int8 (QLoRA) since qdot recurses.
  * `merge()` folds trained adapters into plain dense weights for
    serving/export — the merged tree is a normal checkpoint.
  * B initializes to zero (step-0 model == base model, the standard
    LoRA init); A is scaled-normal.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import NamedSharding
from jax.sharding import PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.ops import quant
from skypilot_tpu.parallel import mesh as mesh_lib
from skypilot_tpu.train import trainer


@dataclasses.dataclass(frozen=True)
class LoraConfig:
    rank: int = 8
    alpha: float = 16.0
    # Layer-stack weight keys to adapt (classic attention-only default;
    # add w_gate/w_up/w_down for full-MLP LoRA).
    target_keys: Tuple[str, ...] = ('wq', 'wv')

    @property
    def scale(self) -> float:
        return self.alpha / self.rank


def _target_shapes(cfg: llama.LlamaConfig) -> Dict[str, Tuple[int, int]]:
    d, f = cfg.dim, cfg.ffn_dim
    qd = cfg.n_heads * cfg.head_dim
    kvd = cfg.n_kv_heads * cfg.head_dim
    return {'wq': (d, qd), 'wk': (d, kvd), 'wv': (d, kvd),
            'wo': (qd, d), 'w_gate': (d, f), 'w_up': (d, f),
            'w_down': (f, d)}


def init_adapters(key: jax.Array, cfg: llama.LlamaConfig,
                  lora_cfg: LoraConfig) -> Dict[str, Any]:
    shapes = _target_shapes(cfg)
    out: Dict[str, Any] = {}
    for name in lora_cfg.target_keys:
        din, dout = shapes[name]
        key, sub = jax.random.split(key)
        out[name] = {
            'a': (jax.random.normal(sub, (cfg.n_layers, din,
                                          lora_cfg.rank), jnp.float32)
                  / jnp.sqrt(din)).astype(cfg.dtype),
            'b': jnp.zeros((cfg.n_layers, lora_cfg.rank, dout),
                           cfg.dtype),
        }
    return out


def adapter_shardings(cfg: llama.LlamaConfig, lora_cfg: LoraConfig,
                      model: Any = llama) -> Dict[str, Any]:
    """A inherits the base weight's input-axis sharding, B its
    output-axis sharding; the rank axis is replicated (it is tiny)."""
    weight_specs = model.param_shardings(cfg)['layers']
    out: Dict[str, Any] = {}
    for name in lora_cfg.target_keys:
        spec = weight_specs.get(name)
        if spec is None or len(spec) != 3:
            # 4-axis specs are MoE expert stacks [L, E, D, F]: per-
            # expert LoRA is not implemented — adapt attention keys.
            raise NotImplementedError(
                f'LoRA target {name!r} is not a [L, D, F] weight of '
                f'this model (adapt attention keys for MoE models)')
        _l, in_spec, out_spec = spec
        out[name] = {'a': P(None, in_spec, None),
                     'b': P(None, None, out_spec)}
    return out


def apply(params: llama.Params, adapters: Dict[str, Any],
          lora_cfg: LoraConfig) -> llama.Params:
    """Param tree with LoraWeight leaves on the adapted keys — feed to
    any forward/decode path (they all project through quant.qdot)."""
    layers = dict(params['layers'])
    for name, ab in adapters.items():
        layers[name] = quant.LoraWeight(base=layers[name], a=ab['a'],
                                        b=ab['b'],
                                        scale=lora_cfg.scale)
    return {**params, 'layers': layers}


def merge(params: llama.Params, adapters: Dict[str, Any],
          lora_cfg: LoraConfig) -> llama.Params:
    """Fold adapters into plain dense weights (serving/export). The
    base must be dense (merge an int8 base by dequantizing first)."""
    layers = dict(params['layers'])
    for name, ab in adapters.items():
        base = layers[name]
        if isinstance(base, quant.QTensor):
            raise ValueError(
                'merge() needs a dense base; dequantize the int8 base '
                'first (QLoRA bases are usually served unmerged via '
                'apply()).')
        delta = jnp.einsum('ldr,lrf->ldf',
                           ab['a'].astype(jnp.float32),
                           ab['b'].astype(jnp.float32))
        layers[name] = (base.astype(jnp.float32)
                        + delta * lora_cfg.scale).astype(base.dtype)
    return {**params, 'layers': layers}


def init_adapter_state(cfg: llama.LlamaConfig, mesh, lora_cfg: LoraConfig,
                       optimizer: optax.GradientTransformation,
                       seed: int = 0, model: Any = llama):
    """(TrainState over adapters, state shardings) — the trainable half
    of a LoRA run; the frozen base rides separately."""
    specs = adapter_shardings(cfg, lora_cfg, model=model)
    to_ns = lambda s: NamedSharding(mesh, s)   # noqa: E731
    adapter_ns = jax.tree.map(to_ns, specs)

    def _init(key):
        adapters = init_adapters(key, cfg, lora_cfg)
        return trainer.TrainState(step=jnp.zeros((), jnp.int32),
                                  params=adapters,
                                  opt_state=optimizer.init(adapters))

    adapters_struct = jax.eval_shape(
        lambda k: init_adapters(k, cfg, lora_cfg), jax.random.PRNGKey(0))
    opt_struct = jax.eval_shape(optimizer.init, adapters_struct)
    # Adam moments mirror the adapter tree: path-suffix spec match
    # (shape matching collides — wq.a and wo.a are identically shaped
    # but transposed-sharded whenever n_heads*head_dim == dim).
    opt_ns = trainer.opt_state_shardings(mesh, specs, opt_struct)
    state_shardings = trainer.TrainState(step=to_ns(P()),
                                         params=adapter_ns,
                                         opt_state=opt_ns)
    state = jax.jit(_init, out_shardings=state_shardings)(
        jax.random.PRNGKey(seed))
    return state, state_shardings


def make_lora_train_step(cfg: llama.LlamaConfig, mesh,
                         optimizer: optax.GradientTransformation,
                         state_shardings, lora_cfg: LoraConfig,
                         model: Any = llama):
    """Jitted SPMD step: gradients and optimizer updates over ADAPTERS
    only; the frozen base params are a sharded input (donated? no —
    reused every step)."""
    base_ns = jax.tree.map(lambda s: NamedSharding(mesh, s),
                           model.param_shardings(cfg))
    batch_sharding = NamedSharding(mesh, P(('dp', 'fsdp'), None))

    if hasattr(model, 'make_loss_fn'):
        # Models with auxiliary losses (mixtral's router terms).
        base_loss = model.make_loss_fn(cfg)

        def loss_fn(adapters, base, tokens):
            return base_loss(apply(base, adapters, lora_cfg), tokens)
    else:
        def loss_fn(adapters, base, tokens):
            params = apply(base, adapters, lora_cfg)
            inputs, targets = tokens[:, :-1], tokens[:, 1:]
            logits = model.forward(params, inputs, cfg)
            return trainer.cross_entropy_loss(logits, targets)

    def step_fn(state, base, batch):
        with mesh_lib.use_mesh(mesh):
            loss, grads = jax.value_and_grad(loss_fn)(
                state.params, base, batch['tokens'])
        updates, new_opt = optimizer.update(grads, state.opt_state,
                                            state.params)
        new_adapters = optax.apply_updates(state.params, updates)
        metrics = {'loss': loss,
                   'grad_norm': optax.global_norm(grads),
                   'step': state.step + 1}
        return trainer.TrainState(step=state.step + 1,
                                  params=new_adapters,
                                  opt_state=new_opt), metrics

    return jax.jit(
        step_fn,
        in_shardings=(state_shardings, base_ns,
                      {'tokens': batch_sharding}),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,))
