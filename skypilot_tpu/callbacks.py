"""Step-timestamp callback for benchmarked training jobs.

Reference: the separate `sky_callback` package (sky/callbacks/,
sky_callback.init/step + Keras/Lightning/Transformers adapters) whose
timestamped step logs the benchmark subsystem turns into sec/step and
$/step. Here it is one dependency-free module shipped inside the
framework wheel, plus a JAX-first convenience (`wrap_step`) instead of
torch-framework adapters.

Protocol (what benchmark/utils.py parses):
    <log_dir>/config.json     {"total_steps": N | null, "start_ts": ...}
    <log_dir>/timestamps.jsonl  one {"step": i, "ts": float} line per step

Only global rank 0 writes (every TPU host runs the same SPMD program;
writing once is enough and avoids N-host merge).
"""
from __future__ import annotations

import contextlib
import functools
import json
import os
import time
from typing import Any, Callable, Optional

ENV_LOG_DIR = 'SKYT_BENCHMARK_LOG_DIR'
DEFAULT_LOG_DIR = '~/.skyt/benchmark_logs/default'

_state: dict = {'fh': None, 'step': 0}


def _is_rank_zero() -> bool:
    return os.environ.get('SKYT_PROCESS_ID', '0') == '0'


def init(log_dir: Optional[str] = None,
         total_steps: Optional[int] = None) -> None:
    """Open the step log. Call once before the train loop."""
    if not _is_rank_zero():
        return
    log_dir = os.path.expanduser(
        log_dir or os.environ.get(ENV_LOG_DIR, DEFAULT_LOG_DIR))
    os.makedirs(log_dir, exist_ok=True)
    with open(os.path.join(log_dir, 'config.json'), 'w') as f:
        json.dump({'total_steps': total_steps, 'start_ts': time.time()}, f)
    # 'w' (not append): a rerun on a reused cluster must not mix two
    # runs' timestamps — the inter-run gap would corrupt sec/step.
    _state['fh'] = open(os.path.join(log_dir, 'timestamps.jsonl'), 'w',
                        buffering=1)   # line-buffered: tail-able live
    _state['step'] = 0


def on_step_end(step: Optional[int] = None) -> None:
    """Record one finished step (monotonic default numbering)."""
    fh = _state.get('fh')
    if fh is None:
        return
    if step is None:
        step = _state['step']
    _state['step'] = step + 1
    fh.write(json.dumps({'step': step, 'ts': time.time()}) + '\n')


@contextlib.contextmanager
def step():
    """`with sky_callback.step():` around each training step."""
    try:
        yield
    finally:
        on_step_end()


def wrap_step(fn: Callable[..., Any]) -> Callable[..., Any]:
    """Wrap a (jitted) train-step callable so every call logs a step.

    Blocks on the result's readiness before stamping (jax dispatch is
    async — without `block_until_ready` the timestamps would measure
    dispatch, not compute)."""

    @functools.wraps(fn)
    def wrapped(*args, **kwargs):
        out = fn(*args, **kwargs)
        try:
            import jax
            jax.block_until_ready(out)
        except Exception:  # noqa: BLE001 — non-jax return values
            pass
        on_step_end()
        return out
    return wrapped


def close() -> None:
    fh = _state.get('fh')
    if fh is not None:
        fh.close()
        _state['fh'] = None
