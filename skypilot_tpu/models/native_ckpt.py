"""Native (orbax) serving checkpoints: params + model config + tokenizer
assets in one directory the serving engine loads directly.

Closes the finetune→serve loop in-framework: `train/lora.py` merges
adapters into a plain parameter tree, `save_serving_ckpt` writes it
(orbax) alongside the model config and the source checkpoint's
tokenizer assets, and `engine_server --ckpt DIR` serves it — no HF
round trip. The reference's recipes hand off between stages only via
HF-format checkpoints on disk (reference
llm/llama-3_1-finetuning/lora.yaml writes torchtune output the serve
recipe re-reads); this path exists because our trainer and engine
share one parameter schema.
"""
from __future__ import annotations

import dataclasses
import json
import os
import shutil
from typing import Any, Optional, Tuple

import jax.numpy as jnp

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

CONFIG_FILE = 'model_config.json'
# Copied verbatim so the serving dir is self-contained for text/chat.
TOKENIZER_ASSETS = ('tokenizer.json', 'tokenizer_config.json',
                    'special_tokens_map.json', 'tokenizer.model')
_FAMILIES = ('llama', 'mixtral')


def _module_for(family: str):
    if family == 'llama':
        from skypilot_tpu.models import llama
        return llama
    if family == 'mixtral':
        from skypilot_tpu.models import mixtral
        return mixtral
    raise ValueError(
        f'unknown model_family {family!r} (expected one of {_FAMILIES})')


def _cfg_to_dict(cfg: Any) -> dict:
    d = dataclasses.asdict(cfg)
    # dtype is a jnp type object; store its canonical name.
    d['dtype'] = jnp.dtype(cfg.dtype).name
    return d


def _cfg_from_dict(family: str, d: dict) -> Any:
    d = dict(d)
    d['dtype'] = jnp.dtype(d['dtype']).type
    if family == 'llama':
        from skypilot_tpu.models import llama
        if d.get('rope_scaling') is not None:
            d['rope_scaling'] = llama.RopeScaling(**d['rope_scaling'])
        return llama.LlamaConfig(**d)
    from skypilot_tpu.models import mixtral
    return mixtral.MixtralConfig(**d)


def save_serving_ckpt(directory: str, cfg: Any, params: Any,
                      model_family: str = 'llama',
                      eos_id: Any = None,
                      tokenizer_src: Optional[str] = None) -> None:
    """Write `params` (orbax, step 0) + model config + tokenizer assets
    to `directory`. `tokenizer_src`: a checkpoint dir whose tokenizer
    assets are copied in, so chat/text endpoints work against the
    result without the original checkpoint."""
    import jax

    from skypilot_tpu.train import checkpoints
    if model_family not in _FAMILIES:
        raise ValueError(f'unknown model_family {model_family!r}')
    directory = os.path.abspath(os.path.expanduser(directory))
    mgr = checkpoints.CheckpointManager(directory, max_to_keep=1)
    mgr.save(0, {'params': jax.device_get(params)})
    mgr.close()
    meta = {'model_family': model_family,
            'eos_id': list(eos_id) if isinstance(eos_id, (tuple, list))
            else eos_id,
            'config': _cfg_to_dict(cfg)}
    with open(os.path.join(directory, CONFIG_FILE), 'w') as f:
        json.dump(meta, f, indent=1)
    if tokenizer_src is not None:
        src = os.path.abspath(os.path.expanduser(tokenizer_src))
        for asset in TOKENIZER_ASSETS:
            p = os.path.join(src, asset)
            if os.path.exists(p):
                shutil.copy(p, os.path.join(directory, asset))


def load_serving_ckpt(directory: str
                      ) -> Tuple[Any, Any, Any, Optional[Any]]:
    """Returns (model_module, cfg, params, eos_id) from a
    save_serving_ckpt directory. Params come back as host arrays; the
    engine device_puts them per its sharding plan."""
    from skypilot_tpu.train import checkpoints
    directory = os.path.abspath(os.path.expanduser(directory))
    cfg_path = os.path.join(directory, CONFIG_FILE)
    if not os.path.exists(cfg_path):
        raise FileNotFoundError(
            f'{cfg_path} not found: not a native serving checkpoint '
            '(write one with models.native_ckpt.save_serving_ckpt, '
            'e.g. finetune_lora.py --merge-out)')
    with open(cfg_path) as f:
        meta = json.load(f)
    family = meta['model_family']
    module = _module_for(family)
    cfg = _cfg_from_dict(family, meta['config'])
    eos = meta.get('eos_id')
    if isinstance(eos, list):
        eos = tuple(eos)
    mgr = checkpoints.CheckpointManager(directory, max_to_keep=1)
    step, tree = mgr.restore_latest_raw()
    mgr.close()
    if step is None:
        raise FileNotFoundError(
            f'no checkpoint steps under {directory}')
    logger.info('loaded native serving checkpoint %s (step %s, %s)',
                directory, step, family)
    return module, cfg, tree['params'], eos
