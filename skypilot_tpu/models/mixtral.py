"""Mixtral-family sparse-MoE transformer in pure functional JAX.

The reference serves Mixtral by shelling out to vLLM with tensor
parallelism (reference llm/mixtral/README.md, serve.yaml:40) and has no
in-framework MoE. Here Mixtral is a first-class model: the attention path
is shared with models/llama.py (GQA + RoPE + flash attention), the FFN is
the sparse-MoE op (ops/moe.py) with experts sharded over the 'ep' mesh
axis, and the whole body is one `lax.scan` over stacked layer weights like
Llama so compile time stays flat in depth.

forward() returns (logits, aux_loss): the router load-balance + z losses
must be added to the task loss during training (train/trainer.py does this
via the model module's `make_loss_fn`).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from skypilot_tpu.models import llama
from skypilot_tpu.ops import moe
from skypilot_tpu.ops import quant

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class MixtralConfig:
    vocab_size: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    max_seq_len: int = 8192
    rope_theta: float = 1e6
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    use_flash_attention: bool = True
    scan_layers: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def moe(self) -> moe.MoEConfig:
        return moe.MoEConfig(num_experts=self.num_experts,
                             top_k=self.top_k,
                             capacity_factor=self.capacity_factor)

    def _attn_cfg(self) -> llama.LlamaConfig:
        """Llama-config view for the shared attention helpers."""
        return llama.LlamaConfig(
            vocab_size=self.vocab_size, dim=self.dim,
            n_layers=self.n_layers, n_heads=self.n_heads,
            n_kv_heads=self.n_kv_heads, ffn_dim=self.ffn_dim,
            max_seq_len=self.max_seq_len, rope_theta=self.rope_theta,
            norm_eps=self.norm_eps, dtype=self.dtype,
            use_flash_attention=self.use_flash_attention)

    @property
    def num_params(self) -> int:
        d, f, l, v = self.dim, self.ffn_dim, self.n_layers, self.vocab_size
        kvd = self.n_kv_heads * self.head_dim
        per_layer = (2 * d * d + 2 * d * kvd          # attention
                     + d * self.num_experts           # router
                     + self.num_experts * 3 * d * f   # experts
                     + 2 * d)                         # norms
        return v * d * 2 + l * per_layer + d

    @property
    def num_active_params(self) -> int:
        """Params touched per token (top_k experts only) — the MFU basis."""
        d, f, l, v = self.dim, self.ffn_dim, self.n_layers, self.vocab_size
        kvd = self.n_kv_heads * self.head_dim
        per_layer = (2 * d * d + 2 * d * kvd + d * self.num_experts
                     + self.top_k * 3 * d * f + 2 * d)
        return v * d * 2 + l * per_layer + d

    def flops_per_token(self, seq_len: int) -> float:
        return (6.0 * self.num_active_params
                + 12.0 * self.n_layers * self.dim * seq_len)


def mixtral_8x7b() -> MixtralConfig:
    return MixtralConfig()


def mixtral_tiny() -> MixtralConfig:
    """Structure-preserving toy config for tests / compile checks."""
    return MixtralConfig(vocab_size=512, dim=128, n_layers=2, n_heads=4,
                         n_kv_heads=2, ffn_dim=256, num_experts=4,
                         top_k=2, max_seq_len=512, rope_theta=10000.0,
                         use_flash_attention=False)


# Params -------------------------------------------------------------- #

def init_params(key: jax.Array, cfg: MixtralConfig) -> Params:
    d, f, l, v = cfg.dim, cfg.ffn_dim, cfg.n_layers, cfg.vocab_size
    hd, nh, nkv, e = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads, cfg.num_experts
    keys = jax.random.split(key, 10)

    def norm_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) /
                jnp.sqrt(fan_in)).astype(cfg.dtype)

    return {
        'embed': norm_init(keys[0], (v, d), d),
        'layers': {
            'wq': norm_init(keys[1], (l, d, nh * hd), d),
            'wk': norm_init(keys[2], (l, d, nkv * hd), d),
            'wv': norm_init(keys[3], (l, d, nkv * hd), d),
            'wo': norm_init(keys[4], (l, nh * hd, d), nh * hd),
            # Router stays fp32: tiny, and routing decisions are
            # numerically sensitive.
            'w_router': (jax.random.normal(keys[5], (l, d, e), jnp.float32)
                         / jnp.sqrt(d)),
            'w_gate': norm_init(keys[6], (l, e, d, f), d),
            'w_up': norm_init(keys[7], (l, e, d, f), d),
            'w_down': norm_init(keys[8], (l, e, f, d), f),
            'ln_attn': jnp.ones((l, d), cfg.dtype),
            'ln_mlp': jnp.ones((l, d), cfg.dtype),
        },
        'final_norm': jnp.ones((d,), cfg.dtype),
        'lm_head': norm_init(keys[9], (v, d), d),
    }


def param_shardings(cfg: MixtralConfig) -> Params:
    """Attention like Llama (fsdp x tp); experts over 'ep', with the
    per-expert matrices additionally fsdp x tp sharded."""
    del cfg
    return {
        'embed': P('tp', 'fsdp'),
        'layers': {
            'wq': P(None, 'fsdp', 'tp'),
            'wk': P(None, 'fsdp', 'tp'),
            'wv': P(None, 'fsdp', 'tp'),
            'wo': P(None, 'tp', 'fsdp'),
            'w_router': P(None, 'fsdp', None),
            'w_gate': P(None, 'ep', 'fsdp', 'tp'),
            'w_up': P(None, 'ep', 'fsdp', 'tp'),
            'w_down': P(None, 'ep', 'tp', 'fsdp'),
            'ln_attn': P(None, None),
            'ln_mlp': P(None, None),
        },
        'final_norm': P(None),
        'lm_head': P('tp', 'fsdp'),
    }


# Model --------------------------------------------------------------- #

def _layer(cfg: MixtralConfig, x: jax.Array, layer_params: Params,
           angles: jax.Array, return_kv: bool = False, cache=None):
    """One block: shared-attention + sparse-MoE FFN.

    Returns (x, aux, kv_out); kv semantics follow llama._layer —
    `cache=(k_cache, v_cache, lengths)` switches to the KV-cache decode
    path, `return_kv` emits this layer's fresh k/v for prefill."""
    x, kv_out = llama.attention_block(cfg._attn_cfg(), x, layer_params,
                                      angles, return_kv=return_kv,
                                      cache=cache)

    mlp_in = llama.rms_norm(x, layer_params['ln_mlp'], cfg.norm_eps)
    # Serving paths (cached decode AND return_kv prefill) pin a drop-free
    # capacity: decode so a request's output cannot depend on which other
    # slots share its batch (the invariant the engine's admission logic
    # relies on), prefill so bucket-padding tokens cannot evict a real
    # token from an expert and logits stay bucket-size-independent.
    # Training keeps the GShard capacity-factor semantics (drops ride
    # the residual).
    if return_kv and x.shape[0] > 1:
        # Batched prefill: route each request's tokens independently
        # (vmap over rows). Joint routing would need a drop-free
        # capacity over ALL N*S wave tokens, making the [T, E, C]
        # dispatch buffers quadratic in wave tokens (OOM territory for
        # long buckets); per-row routing keeps them linear in N and is
        # exactly the per-request independence the engine relies on.
        cap = moe.drop_free_capacity(x.shape[1])

        def one_row(row):
            out, row_aux = moe.sparse_moe(
                row[None], layer_params['w_router'],
                layer_params['w_gate'], layer_params['w_up'],
                layer_params['w_down'], cfg.moe, capacity=cap)
            return out[0], row_aux

        moe_out, aux = jax.vmap(one_row)(mlp_in)
        aux = jnp.sum(aux)
    else:
        serving = cache is not None or return_kv
        n_tokens = x.shape[0] * x.shape[1]
        capacity = moe.drop_free_capacity(n_tokens) if serving else None
        moe_out, aux = moe.sparse_moe(
            mlp_in, layer_params['w_router'], layer_params['w_gate'],
            layer_params['w_up'], layer_params['w_down'], cfg.moe,
            capacity=capacity)
    x = x + moe_out
    x = llama._shard(x, llama.ACT_SPEC)
    return x, aux, kv_out


def forward(params: Params, tokens: jax.Array, cfg: MixtralConfig,
            positions: Optional[jax.Array] = None,
            return_kv: bool = False):
    """tokens [B, S] int32 -> (logits [B, S, V] fp32, aux loss scalar).

    With return_kv=True (serving prefill) returns (logits, kv_dict)
    instead — the aux loss is a training-only quantity, and this matches
    llama.forward's serving contract so serve/engine.py can drive either
    model family."""
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s)
    angles = llama.rope_frequencies(cfg._attn_cfg(), positions)
    x = quant.qtake(params['embed'], tokens, cfg.dtype)
    x = llama._shard(x, llama.ACT_SPEC)

    layer_fn = functools.partial(_layer, cfg, return_kv=return_kv)
    if cfg.remat and not return_kv:
        layer_fn = jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims)

    kv = None
    if cfg.scan_layers:
        def scan_body(carry, layer_params):
            x, aux, layer_kv = layer_fn(carry, layer_params, angles)
            return x, ((aux, layer_kv) if return_kv else aux)
        x, ys = jax.lax.scan(scan_body, x, params['layers'])
        if return_kv:
            aux_per_layer, kv = ys
        else:
            aux_per_layer = ys
        aux = jnp.sum(aux_per_layer)
    else:
        aux = jnp.zeros((), jnp.float32)
        ks, vs = [], []
        for i in range(cfg.n_layers):
            layer_params = jax.tree.map(lambda p: p[i], params['layers'])
            x, layer_aux, layer_kv = layer_fn(x, layer_params, angles)
            aux = aux + layer_aux
            if return_kv:
                ks.append(layer_kv[0])
                vs.append(layer_kv[1])
        if return_kv:
            kv = (jnp.stack(ks), jnp.stack(vs))

    x = llama.rms_norm(x, params['final_norm'], cfg.norm_eps)
    logits = quant.qeinsum('bsd,vd->bsv', x, params['lm_head'],
                           preferred_element_type=jnp.float32)
    logits = llama._shard(logits, llama.LOGITS_SPEC)
    if return_kv:
        return logits, {'k': kv[0], 'v': kv[1]}
    return logits, aux


# Decode path (KV cache) ---------------------------------------------- #
#
# Serving counterpart for MoE models: the reference serves Mixtral only by
# shelling out to vLLM (reference llm/mixtral/serve.yaml:40); here the
# cached decode step is in-framework so serve/engine.py's continuous
# batching drives Mixtral exactly like Llama. The KV cache layout is the
# attention path's (llama.init_kv_cache); the MoE FFN has no cache state.

def init_kv_cache(cfg: MixtralConfig, batch_size: int,
                  max_len: int, quantized: bool = False) -> Params:
    return llama.init_kv_cache(cfg._attn_cfg(), batch_size, max_len,
                               quantized=quantized)


kv_cache_specs = llama.kv_cache_specs


def decode_step(params: Params, cache: Params, lengths: jax.Array,
                tokens: jax.Array, cfg: MixtralConfig):
    """One token for every slot; llama.decode_tail with the sparse-MoE
    FFN in the layer body. Returns (logits [B, V], new_cache).

    The layer body pins capacity >= tokens for the cache path (see
    _layer), so a decode step NEVER capacity-drops a token and a
    request's outputs cannot depend on which other slots share its
    batch — unlike a long prefill/training batch, where over-subscribed
    experts drop tokens to the residual by design."""
    def layer_body(x, layer_params, angles, cache_triple):
        x, _aux, kv = _layer(cfg, x, layer_params, angles,
                             cache=cache_triple)
        return x, kv

    return llama.decode_tail(params, cache, lengths, tokens,
                             cfg._attn_cfg(), layer_body)


def make_loss_fn(cfg: MixtralConfig):
    """Next-token CE + router aux losses; trainer-compatible signature."""
    from skypilot_tpu.train import trainer

    def loss_fn(params, tokens):
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        logits, aux = forward(params, inputs, cfg)
        return trainer.cross_entropy_loss(logits, targets) + aux
    return loss_fn


# Same tree shape as llama's (extra dense leaves — w_router, norms —
# pass through): reuse its quantization + spec-rewrite wholesale. The
# per-expert [L, E, D, F] mats get per-(expert, out-channel) scales and
# keep their 'ep' axis, dropping the contracted one.
quantize_params = llama.quantize_params


def quantized_param_shardings(cfg: MixtralConfig) -> Params:
    return llama.quantized_spec_tree(param_shardings(cfg))
