"""Llama-3 family in pure functional JAX, TPU-first.

This is the in-repo replacement for the reference's recipe-level HF
torch-xla training (examples/tpu/v6e/train-llama3-8b.yaml) and the model
behind the JetStream-style serving path. Design points:

  * params are a flat pytree (nested dict of jnp arrays) with layer weights
    STACKED on a leading [L, ...] axis -> the whole transformer body is one
    `lax.scan`, so XLA compiles one layer and reuses it (compile time and
    code size stay flat as L grows).
  * every param / activation has an explicit PartitionSpec over the
    canonical mesh axes (parallel/mesh.py): fsdp shards params, tp shards
    heads/ffn megatron-style, dp/fsdp shard the batch, sp shards sequence.
  * compute in bfloat16 on the MXU, fp32 for softmax and the final logits;
    `jax.checkpoint` (remat) around each layer trades FLOPs for HBM.
  * GQA (grouped-query attention), RoPE, RMSNorm, SwiGLU — Llama-3
    architecture; attention dispatches to the Pallas flash kernel on TPU
    (ops/flash_attention.py) and falls back to a masked-einsum reference
    path elsewhere.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    use_flash_attention: bool = True
    # vjp-friendly toggle for scanning layers; False unrolls (debugging).
    scan_layers: bool = True

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads

    @property
    def num_params(self) -> int:
        """Exact dense param count (embeddings counted once; lm_head
        untied like Llama-3-8B)."""
        d, f, l, v = self.dim, self.ffn_dim, self.n_layers, self.vocab_size
        kvd = self.n_kv_heads * self.head_dim
        per_layer = (d * d          # wq
                     + 2 * d * kvd  # wk, wv
                     + d * d        # wo
                     + 3 * d * f    # gate, up, down
                     + 2 * d)       # norms
        return v * d * 2 + l * per_layer + d

    def flops_per_token(self, seq_len: int) -> float:
        """Training FLOPs/token: 6*N for matmuls + 12*L*D*S attention
        (standard MFU accounting, no causal halving)."""
        return 6.0 * self.num_params + 12.0 * self.n_layers * self.dim * seq_len


# Presets ------------------------------------------------------------- #

def llama3_8b() -> LlamaConfig:
    return LlamaConfig()


def llama3_1b() -> LlamaConfig:
    """Llama-3.2-1B shape."""
    return LlamaConfig(dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
                       ffn_dim=8192)


def llama_tiny() -> LlamaConfig:
    """Structure-preserving toy config for tests / compile checks."""
    return LlamaConfig(vocab_size=512, dim=128, n_layers=2, n_heads=4,
                       n_kv_heads=2, ffn_dim=256, max_seq_len=512,
                       rope_theta=10000.0, use_flash_attention=False)


# Params -------------------------------------------------------------- #

def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    d, f, l, v = cfg.dim, cfg.ffn_dim, cfg.n_layers, cfg.vocab_size
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    keys = jax.random.split(key, 9)

    def norm_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) /
                jnp.sqrt(fan_in)).astype(cfg.dtype)

    return {
        'embed': norm_init(keys[0], (v, d), d),
        'layers': {
            'wq': norm_init(keys[1], (l, d, nh * hd), d),
            'wk': norm_init(keys[2], (l, d, nkv * hd), d),
            'wv': norm_init(keys[3], (l, d, nkv * hd), d),
            'wo': norm_init(keys[4], (l, nh * hd, d), nh * hd),
            'w_gate': norm_init(keys[5], (l, d, f), d),
            'w_up': norm_init(keys[6], (l, d, f), d),
            'w_down': norm_init(keys[7], (l, f, d), f),
            'ln_attn': jnp.ones((l, d), cfg.dtype),
            'ln_mlp': jnp.ones((l, d), cfg.dtype),
        },
        'final_norm': jnp.ones((d,), cfg.dtype),
        'lm_head': norm_init(keys[8], (v, d), d),
    }


def param_shardings(cfg: LlamaConfig) -> Params:
    """PartitionSpecs, same tree structure as init_params.

    fsdp shards the model dim, tp shards heads/ffn (megatron: column-then-
    row so each block needs one reduce per projection pair).
    """
    del cfg
    return {
        'embed': P('tp', 'fsdp'),
        'layers': {
            'wq': P(None, 'fsdp', 'tp'),
            'wk': P(None, 'fsdp', 'tp'),
            'wv': P(None, 'fsdp', 'tp'),
            'wo': P(None, 'tp', 'fsdp'),
            'w_gate': P(None, 'fsdp', 'tp'),
            'w_up': P(None, 'fsdp', 'tp'),
            'w_down': P(None, 'tp', 'fsdp'),
            'ln_attn': P(None, None),
            'ln_mlp': P(None, None),
        },
        'final_norm': P(None),
        'lm_head': P('tp', 'fsdp'),
    }


ACT_SPEC = P(('dp', 'fsdp'), 'sp', None)          # [B, S, D]
LOGITS_SPEC = P(('dp', 'fsdp'), 'sp', 'tp')       # [B, S, V]


# Model --------------------------------------------------------------- #

def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (norm * weight.astype(jnp.float32)).astype(x.dtype)


def rope_frequencies(cfg: LlamaConfig, positions: jax.Array) -> jax.Array:
    """[S, head_dim//2] complex-free rotation angles."""
    half = cfg.head_dim // 2
    freqs = 1.0 / (cfg.rope_theta **
                   (jnp.arange(0, half, dtype=jnp.float32) / half))
    return positions[:, None].astype(jnp.float32) * freqs[None, :]


def apply_rope(x: jax.Array, angles: jax.Array) -> jax.Array:
    """x: [B, S, H, hd]; angles: [S, hd//2] (or [B, S, hd//2])."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    if angles.ndim == 2:
        cos = jnp.cos(angles)[None, :, None, :]
        sin = jnp.sin(angles)[None, :, None, :]
    else:
        cos = jnp.cos(angles)[:, :, None, :]
        sin = jnp.sin(angles)[:, :, None, :]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def _reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         causal: bool = True) -> jax.Array:
    """Masked-einsum attention: [B, S, H, hd] x [B, S, KV, hd]. GQA via
    head broadcasting. fp32 softmax."""
    b, s, h, hd = q.shape
    kv_heads = k.shape[2]
    group = h // kv_heads
    q = q.reshape(b, s, kv_heads, group, hd)
    scores = jnp.einsum('bqkgh,bskh->bkgqs', q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(hd).astype(jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum('bkgqs,bskh->bqkgh', probs.astype(v.dtype), v)
    return out.reshape(b, s, h, hd)


def _kernel_compatible(q: jax.Array) -> bool:
    """Flash kernel constraints: lane-width head dim, block-divisible seq."""
    seq, head_dim = q.shape[1], q.shape[3]
    if head_dim % 128 != 0:
        return False
    from skypilot_tpu.ops import flash_attention as fa
    block = min(fa.DEFAULT_BLOCK_Q, seq)
    return seq >= 128 and seq % block == 0


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              cfg: LlamaConfig) -> jax.Array:
    if cfg.use_flash_attention and _kernel_compatible(q):
        from skypilot_tpu.ops import flash_attention
        return flash_attention.flash_attention(q, k, v, causal=True)
    return _reference_attention(q, k, v)


def _layer(cfg: LlamaConfig, x: jax.Array, layer_params: Params,
           angles: jax.Array) -> jax.Array:
    b, s, d = x.shape
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    attn_in = rms_norm(x, layer_params['ln_attn'], cfg.norm_eps)
    q = (attn_in @ layer_params['wq']).reshape(b, s, h, hd)
    k = (attn_in @ layer_params['wk']).reshape(b, s, kv, hd)
    v = (attn_in @ layer_params['wv']).reshape(b, s, kv, hd)
    q = apply_rope(q, angles)
    k = apply_rope(k, angles)
    attn_out = attention(q, k, v, cfg).reshape(b, s, h * hd)
    x = x + attn_out @ layer_params['wo']
    x = _shard(x, ACT_SPEC)

    mlp_in = rms_norm(x, layer_params['ln_mlp'], cfg.norm_eps)
    gate = jax.nn.silu(mlp_in @ layer_params['w_gate'])
    up = mlp_in @ layer_params['w_up']
    x = x + (gate * up) @ layer_params['w_down']
    return _shard(x, ACT_SPEC)


def _shard(x: jax.Array, spec: P) -> jax.Array:
    """with_sharding_constraint if we're under a mesh; no-op otherwise."""
    try:
        return jax.lax.with_sharding_constraint(x, spec)
    except (ValueError, RuntimeError):
        return x


def forward(params: Params, tokens: jax.Array,
            cfg: LlamaConfig,
            positions: Optional[jax.Array] = None) -> jax.Array:
    """tokens [B, S] int32 -> logits [B, S, V] float32."""
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s)
    angles = rope_frequencies(cfg, positions)
    x = params['embed'][tokens].astype(cfg.dtype)
    x = _shard(x, ACT_SPEC)

    layer_fn = functools.partial(_layer, cfg)
    if cfg.remat:
        layer_fn = jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims)

    if cfg.scan_layers:
        def scan_body(carry, layer_params):
            return layer_fn(carry, layer_params, angles), None
        x, _ = jax.lax.scan(scan_body, x, params['layers'])
    else:
        for i in range(cfg.n_layers):
            layer_params = jax.tree.map(lambda p: p[i], params['layers'])
            x = layer_fn(x, layer_params, angles)

    x = rms_norm(x, params['final_norm'], cfg.norm_eps)
    logits = jnp.einsum('bsd,vd->bsv', x, params['lm_head'],
                        preferred_element_type=jnp.float32)
    return _shard(logits, LOGITS_SPEC)
