"""Llama-3 family in pure functional JAX, TPU-first.

This is the in-repo replacement for the reference's recipe-level HF
torch-xla training (examples/tpu/v6e/train-llama3-8b.yaml) and the model
behind the JetStream-style serving path. Design points:

  * params are a flat pytree (nested dict of jnp arrays) with layer weights
    STACKED on a leading [L, ...] axis -> the whole transformer body is one
    `lax.scan`, so XLA compiles one layer and reuses it (compile time and
    code size stay flat as L grows).
  * every param / activation has an explicit PartitionSpec over the
    canonical mesh axes (parallel/mesh.py): fsdp shards params, tp shards
    heads/ffn megatron-style, dp/fsdp shard the batch, sp shards sequence.
  * compute in bfloat16 on the MXU, fp32 for softmax and the final logits;
    `jax.checkpoint` (remat) around each layer trades FLOPs for HBM.
  * GQA (grouped-query attention), RoPE, RMSNorm, SwiGLU — Llama-3
    architecture; attention dispatches to the Pallas flash kernel on TPU
    (ops/flash_attention.py) and falls back to a masked-einsum reference
    path elsewhere.
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from skypilot_tpu.ops import quant
from skypilot_tpu.parallel.mesh import shard as _shard

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class RopeScaling:
    """Llama-3.1-style RoPE frequency scaling (rope_type='llama3' in HF
    checkpoints). Frequencies below high_freq_wavelen are kept, above
    low_freq_wavelen divided by `factor`, in between smoothly
    interpolated — transformers' _compute_llama3_parameters."""
    factor: float = 8.0
    low_freq_factor: float = 1.0
    high_freq_factor: float = 4.0
    original_max_position_embeddings: int = 8192


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 8
    ffn_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    remat: bool = True
    use_flash_attention: bool = True
    # Ring attention over the 'sp' axis (long context): requires a mesh
    # with sp > 1 active via parallel.mesh.use_mesh (the trainer does
    # this automatically).
    ring_attention: bool = False
    # vjp-friendly toggle for scanning layers; False unrolls (debugging).
    scan_layers: bool = True
    # Llama-3.1 long-context RoPE scaling (None = plain rope_theta).
    rope_scaling: Optional[RopeScaling] = None
    # Q/K/V projection biases (Qwen2-family checkpoints; Llama
    # declares attention_bias in its HF config). Adds bq/bk/bv leaves.
    attention_bias: bool = False
    # Output-projection bias: HF Llama with attention_bias=True also
    # biases o_proj; Qwen2 biases ONLY q/k/v. Adds a bo leaf.
    attention_out_bias: bool = False
    # Family knobs that make this config span the Llama lineage
    # (Llama/Qwen2/Gemma — HF's modeling_llama descendants):
    # explicit head_dim (Gemma: n_heads * head_dim != dim), MLP
    # activation ('silu' | 'gelu_tanh'), and input-embedding scale
    # (Gemma multiplies by sqrt(dim)). Gemma's (1+w) RMSNorm is folded
    # into the stored weights at conversion time instead.
    head_dim_override: Optional[int] = None
    mlp_act: str = 'silu'
    embed_scale: float = 1.0
    # lm_head shares the embedding matrix (Gemma always; small
    # Llama/Qwen2 checkpoints via tie_word_embeddings). Param/FLOP
    # accounting counts the matrix once, and the engine keeps ONE
    # device copy.
    tied_embeddings: bool = False
    # int8-weight matmuls through the pallas in-kernel-dequant kernel
    # (ops/int8_matmul.py): 'tpu' on-chip, 'interpret' for CPU tests,
    # None = XLA path. The serving engine sets this on single-device
    # TPU (a pallas_call is opaque to GSPMD, so mesh serving keeps the
    # XLA path). Training never sets it.
    int8_kernel: Optional[str] = None
    # Decode attention through the pallas online-softmax kernel
    # (ops/decode_attention.py): 'tpu' on-chip, 'interpret' for CPU
    # tests, None (default) = the _cached_attention einsum path. The
    # serving engine sets it only on explicit opt-in
    # (SKYT_DECODE_KERNEL=1): on v5e the per-layer einsum path
    # measured faster (see the kernel's module docstring). Opaque to
    # GSPMD, like int8_kernel — mesh serving keeps the einsum path.
    attn_kernel: Optional[str] = None

    @property
    def head_dim(self) -> int:
        if self.head_dim_override is not None:
            return self.head_dim_override
        return self.dim // self.n_heads

    @property
    def num_params(self) -> int:
        """Exact dense param count (tied_embeddings counts the
        embedding/lm_head matrix once)."""
        d, f, l, v = self.dim, self.ffn_dim, self.n_layers, self.vocab_size
        qd = self.n_heads * self.head_dim
        kvd = self.n_kv_heads * self.head_dim
        per_layer = (d * qd         # wq
                     + 2 * d * kvd  # wk, wv
                     + qd * d       # wo
                     + 3 * d * f    # gate, up, down
                     + 2 * d)       # norms
        if self.attention_bias:
            per_layer += qd + 2 * kvd  # bq, bk, bv
        if self.attention_out_bias:
            per_layer += d             # bo
        embed_params = v * d * (1 if self.tied_embeddings else 2)
        return embed_params + l * per_layer + d

    def flops_per_token(self, seq_len: int) -> float:
        """Training FLOPs/token: 6*N for matmuls + 12*L*D*S attention
        (standard MFU accounting, no causal halving)."""
        return 6.0 * self.num_params + 12.0 * self.n_layers * self.dim * seq_len


# Presets ------------------------------------------------------------- #

def llama3_8b() -> LlamaConfig:
    return LlamaConfig()


def llama3_1b() -> LlamaConfig:
    """Llama-3.2-1B shape."""
    return LlamaConfig(dim=2048, n_layers=16, n_heads=32, n_kv_heads=8,
                       ffn_dim=8192)


def qwen2_7b() -> LlamaConfig:
    """Qwen2/2.5-7B shape (q/k/v biases)."""
    return LlamaConfig(vocab_size=152064, dim=3584, n_layers=28,
                       n_heads=28, n_kv_heads=4, ffn_dim=18944,
                       max_seq_len=32768, rope_theta=1e6,
                       norm_eps=1e-6, attention_bias=True)


def llama_tiny() -> LlamaConfig:
    """Structure-preserving toy config for tests / compile checks."""
    return LlamaConfig(vocab_size=512, dim=128, n_layers=2, n_heads=4,
                       n_kv_heads=2, ffn_dim=256, max_seq_len=512,
                       rope_theta=10000.0, use_flash_attention=False)


# Params -------------------------------------------------------------- #

def init_params(key: jax.Array, cfg: LlamaConfig) -> Params:
    d, f, l, v = cfg.dim, cfg.ffn_dim, cfg.n_layers, cfg.vocab_size
    hd, nh, nkv = cfg.head_dim, cfg.n_heads, cfg.n_kv_heads
    keys = jax.random.split(key, 9)

    def norm_init(k, shape, fan_in):
        return (jax.random.normal(k, shape, jnp.float32) /
                jnp.sqrt(fan_in)).astype(cfg.dtype)

    layers = {
        'wq': norm_init(keys[1], (l, d, nh * hd), d),
        'wk': norm_init(keys[2], (l, d, nkv * hd), d),
        'wv': norm_init(keys[3], (l, d, nkv * hd), d),
        'wo': norm_init(keys[4], (l, nh * hd, d), nh * hd),
        'w_gate': norm_init(keys[5], (l, d, f), d),
        'w_up': norm_init(keys[6], (l, d, f), d),
        'w_down': norm_init(keys[7], (l, f, d), f),
        'ln_attn': jnp.ones((l, d), cfg.dtype),
        'ln_mlp': jnp.ones((l, d), cfg.dtype),
    }
    if cfg.attention_bias:
        layers.update({
            'bq': jnp.zeros((l, nh * hd), cfg.dtype),
            'bk': jnp.zeros((l, nkv * hd), cfg.dtype),
            'bv': jnp.zeros((l, nkv * hd), cfg.dtype),
        })
    if cfg.attention_out_bias:
        layers['bo'] = jnp.zeros((l, d), cfg.dtype)
    embed = norm_init(keys[0], (v, d), d)
    return {
        'embed': embed,
        'layers': layers,
        'final_norm': jnp.ones((d,), cfg.dtype),
        'lm_head': (embed if cfg.tied_embeddings
                    else norm_init(keys[8], (v, d), d)),
    }


# Weight leaves quantized for serving; shared with mixtral (whose param
# tree has the same top-level shape plus extra dense leaves like
# w_router, which the dict-copy passes through untouched).
QUANTIZED_LAYER_KEYS = ('wq', 'wk', 'wv', 'wo', 'w_gate', 'w_up',
                        'w_down')


def quantize_params(params: Params) -> Params:
    """Weight-only int8 for serving (ops/quant.py): every matmul weight
    gets a per-output-channel scale; norms stay dense. forward /
    decode_step accept the result directly (all weight sites go through
    quant.qdot / qeinsum / qtake). Training never uses this.
    Structure-generic: mixtral aliases it."""
    layers = dict(params['layers'])
    for name in QUANTIZED_LAYER_KEYS:
        layers[name] = quant.quantize(layers[name], reduce_axes=(-2,))
    return {
        'embed': quant.quantize(params['embed'], reduce_axes=(-1,)),
        'layers': layers,
        'final_norm': params['final_norm'],
        'lm_head': quant.quantize(params['lm_head'], reduce_axes=(-1,)),
    }


def quantized_spec_tree(ps: Params) -> Params:
    """Rewrite a param_shardings tree for a quantize_params tree: each
    quantized weight becomes QTensor(q=<dense spec>, scale=<spec minus
    the reduced axis>), so int8 serving composes with a tp/ep mesh.
    The single home of the quantized-spec convention (mixtral reuses
    it on its own param_shardings)."""
    layers = dict(ps['layers'])
    for name in QUANTIZED_LAYER_KEYS:
        layers[name] = quant.qtensor_spec(layers[name], reduce_axis=-2)
    return {
        'embed': quant.qtensor_spec(ps['embed'], reduce_axis=-1),
        'layers': layers,
        'final_norm': ps['final_norm'],
        'lm_head': quant.qtensor_spec(ps['lm_head'], reduce_axis=-1),
    }


def quantized_param_shardings(cfg: LlamaConfig) -> Params:
    return quantized_spec_tree(param_shardings(cfg))


def param_shardings(cfg: LlamaConfig) -> Params:
    """PartitionSpecs, same tree structure as init_params.

    fsdp shards the model dim, tp shards heads/ffn (megatron: column-then-
    row so each block needs one reduce per projection pair).
    """
    layers = {
        'wq': P(None, 'fsdp', 'tp'),
        'wk': P(None, 'fsdp', 'tp'),
        'wv': P(None, 'fsdp', 'tp'),
        'wo': P(None, 'tp', 'fsdp'),
        'w_gate': P(None, 'fsdp', 'tp'),
        'w_up': P(None, 'fsdp', 'tp'),
        'w_down': P(None, 'tp', 'fsdp'),
        'ln_attn': P(None, None),
        'ln_mlp': P(None, None),
    }
    if cfg.attention_bias:
        layers.update({'bq': P(None, 'tp'), 'bk': P(None, 'tp'),
                       'bv': P(None, 'tp')})
    if cfg.attention_out_bias:
        layers['bo'] = P(None, 'fsdp')
    return {
        'embed': P('tp', 'fsdp'),
        'layers': layers,
        'final_norm': P(None),
        'lm_head': P('tp', 'fsdp'),
    }


ACT_SPEC = P(('dp', 'fsdp'), 'sp', None)          # [B, S, D]
LOGITS_SPEC = P(('dp', 'fsdp'), 'sp', 'tp')       # [B, S, V]


# Model --------------------------------------------------------------- #

def _mlp_act(cfg: LlamaConfig):
    if cfg.mlp_act == 'silu':
        return jax.nn.silu
    if cfg.mlp_act == 'gelu_tanh':      # Gemma
        return functools.partial(jax.nn.gelu, approximate=True)
    raise ValueError(f'unsupported mlp_act {cfg.mlp_act!r}')


def _embed(params: Params, tokens: jax.Array,
           cfg: LlamaConfig) -> jax.Array:
    x = quant.qtake(params['embed'], tokens, cfg.dtype)
    if cfg.embed_scale != 1.0:
        # Gemma scales input embeddings by sqrt(dim), with the factor
        # rounded to the activation dtype (HF casts the normalizer).
        x = x * jnp.asarray(cfg.embed_scale, cfg.dtype)
    return x


def rms_norm(x: jax.Array, weight: jax.Array, eps: float) -> jax.Array:
    x32 = x.astype(jnp.float32)
    norm = x32 * jax.lax.rsqrt(
        jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps)
    return (norm * weight.astype(jnp.float32)).astype(x.dtype)


def rope_frequencies(cfg: LlamaConfig, positions: jax.Array) -> jax.Array:
    """[S, head_dim//2] complex-free rotation angles."""
    half = cfg.head_dim // 2
    freqs = 1.0 / (cfg.rope_theta **
                   (jnp.arange(0, half, dtype=jnp.float32) / half))
    if cfg.rope_scaling is not None:
        rs = cfg.rope_scaling
        wavelen = 2.0 * jnp.pi / freqs
        low_wl = rs.original_max_position_embeddings / rs.low_freq_factor
        high_wl = rs.original_max_position_embeddings / rs.high_freq_factor
        smooth = ((rs.original_max_position_embeddings / wavelen
                   - rs.low_freq_factor)
                  / (rs.high_freq_factor - rs.low_freq_factor))
        smoothed = ((1.0 - smooth) * freqs / rs.factor + smooth * freqs)
        freqs = jnp.where(
            wavelen < high_wl, freqs,
            jnp.where(wavelen > low_wl, freqs / rs.factor, smoothed))
    return positions[:, None].astype(jnp.float32) * freqs[None, :]


def apply_rope(x: jax.Array, angles: jax.Array) -> jax.Array:
    """x: [B, S, H, hd]; angles: [S, hd//2] (or [B, S, hd//2])."""
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    if angles.ndim == 2:
        cos = jnp.cos(angles)[None, :, None, :]
        sin = jnp.sin(angles)[None, :, None, :]
    else:
        cos = jnp.cos(angles)[:, :, None, :]
        sin = jnp.sin(angles)[:, :, None, :]
    out1 = x1 * cos - x2 * sin
    out2 = x2 * cos + x1 * sin
    return jnp.concatenate([out1, out2], axis=-1).astype(x.dtype)


def _reference_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                         causal: bool = True) -> jax.Array:
    """Masked-einsum attention: [B, S, H, hd] x [B, S, KV, hd]. GQA via
    head broadcasting. fp32 softmax."""
    b, s, h, hd = q.shape
    kv_heads = k.shape[2]
    group = h // kv_heads
    q = q.reshape(b, s, kv_heads, group, hd)
    scores = jnp.einsum('bqkgh,bskh->bkgqs', q, k,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(hd).astype(jnp.float32)
    if causal:
        mask = jnp.tril(jnp.ones((s, s), dtype=bool))
        scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum('bkgqs,bskh->bqkgh', probs.astype(v.dtype), v)
    return out.reshape(b, s, h, hd)


def _extend_attention(q: jax.Array, k_pre: jax.Array, v_pre: jax.Array,
                      k: jax.Array, v: jax.Array) -> jax.Array:
    """Prefix-extend attention: suffix queries q [B, S, H, hd] over
    concat(prefix, suffix) keys — the prefill half of prefix-KV reuse.
    Every prefix position is a REAL token (the engine slices entries to
    grid-aligned true lengths), so the mask is: prefix fully visible,
    suffix causal with its positions offset by the prefix length."""
    b, s, h, hd = q.shape
    s_pre = k_pre.shape[1]
    kv_heads = k.shape[2]
    group = h // kv_heads
    kf = jnp.concatenate([k_pre.astype(k.dtype), k], axis=1)
    vf = jnp.concatenate([v_pre.astype(v.dtype), v], axis=1)
    qg = q.reshape(b, s, kv_heads, group, hd)
    scores = jnp.einsum('bqkgh,bskh->bkgqs', qg, kf,
                        preferred_element_type=jnp.float32)
    scores = scores / jnp.sqrt(hd).astype(jnp.float32)
    t = jnp.arange(s_pre + s)
    i = jnp.arange(s)
    mask = (t[None, :] < s_pre) | (t[None, :] - s_pre <= i[:, None])
    scores = jnp.where(mask[None, None, None], scores, -1e30)
    probs = jax.nn.softmax(scores, axis=-1)
    out = jnp.einsum('bkgqs,bskh->bqkgh', probs.astype(vf.dtype), vf)
    return out.reshape(b, s, h, hd)


def _kernel_compatible(q: jax.Array) -> bool:
    """Flash kernel constraints: lane-width head dim, block-divisible seq."""
    seq, head_dim = q.shape[1], q.shape[3]
    if head_dim % 128 != 0:
        return False
    from skypilot_tpu.ops import flash_attention as fa
    block = min(fa.DEFAULT_BLOCK_Q, seq)
    return seq >= 128 and seq % block == 0


def _ring_attention_sharded(q: jax.Array, k: jax.Array,
                            v: jax.Array, mesh) -> jax.Array:
    """Ring attention over the 'sp'-sharded sequence (parallel/ring.py):
    KV chunks rotate around the ring via nearest-neighbor ppermute, so
    long-context attention never materializes the full sequence on one
    chip. q/k/v are [B, S, H|KV, hd] in model layout."""
    from skypilot_tpu.parallel import mesh as mesh_lib
    from skypilot_tpu.parallel import ring
    q_spec = P(('dp', 'fsdp'), 'sp', 'tp', None)

    def _ring(ql, kl, vl):
        return ring.ring_attention_bshd(ql, kl, vl, axis_name='sp')

    return mesh_lib.compat_shard_map(
        _ring, mesh=mesh,
        in_specs=(q_spec, q_spec, q_spec),
        out_specs=q_spec, check_vma=False)(q, k, v)


def attention(q: jax.Array, k: jax.Array, v: jax.Array,
              cfg: LlamaConfig) -> jax.Array:
    if cfg.ring_attention:
        from skypilot_tpu.parallel import mesh as mesh_lib
        mesh = mesh_lib.current_mesh()
        if mesh is None:
            # Refuse rather than silently trace dense attention: the jit
            # cache is keyed on shapes only, so a dense trace here would
            # shadow the ring path for identical shapes later — OOM at
            # exactly the lengths ring attention exists for.
            raise ValueError(
                'cfg.ring_attention=True but no mesh is active; wrap '
                'the call in parallel.mesh.use_mesh(mesh) (the trainer '
                'does this automatically), or unset the flag for dense '
                'eval.')
        if mesh.shape.get('sp', 1) > 1:
            return _ring_attention_sharded(q, k, v, mesh)
    if cfg.use_flash_attention and _kernel_compatible(q):
        from skypilot_tpu.ops import flash_attention
        return flash_attention.flash_attention(q, k, v, causal=True)
    return _reference_attention(q, k, v)


def _layer(cfg: LlamaConfig, x: jax.Array, layer_params: Params,
           angles: jax.Array, return_kv: bool = False, cache=None,
           prefix=None):
    """One transformer block, shared by training forward, prefill and
    cached decode. `cache=(k_cache, v_cache, lengths)` switches attention
    to the KV-cache path (q of length 1 against the full cache row);
    `return_kv` additionally emits this layer's fresh k/v (prefill);
    `prefix=(k_pre, v_pre)` ([B, S_pre, KV, hd] real tokens) switches
    prefill to the extend path (prefix-KV reuse)."""
    x, kv_out = attention_block(cfg, x, layer_params, angles,
                                return_kv=return_kv, cache=cache,
                                prefix=prefix)

    mlp_in = rms_norm(x, layer_params['ln_mlp'], cfg.norm_eps)
    kern = getattr(cfg, 'int8_kernel', None)
    gate = _mlp_act(cfg)(quant.qdot(mlp_in, layer_params['w_gate'],
                                    kernel=kern))
    up = quant.qdot(mlp_in, layer_params['w_up'], kernel=kern)
    x = x + quant.qdot(gate * up, layer_params['w_down'],
                       kernel=kern)
    x = _shard(x, ACT_SPEC)
    return x, kv_out


def attention_block(cfg: LlamaConfig, x: jax.Array, layer_params: Params,
                    angles: jax.Array, return_kv: bool = False,
                    cache=None, prefix=None):
    """Pre-norm attention sub-block with residual: the piece shared by
    Llama and the MoE models (mixtral swaps only the FFN). Returns
    (x_after_residual, kv_out) with kv semantics as in `_layer`."""
    b, s, d = x.shape
    h, kv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim

    attn_in = rms_norm(x, layer_params['ln_attn'], cfg.norm_eps)
    kern = getattr(cfg, 'int8_kernel', None)
    q = quant.qdot(attn_in, layer_params['wq'], kernel=kern)
    k = quant.qdot(attn_in, layer_params['wk'], kernel=kern)
    v = quant.qdot(attn_in, layer_params['wv'], kernel=kern)
    if 'bq' in layer_params:      # Qwen2-style q/k/v biases
        q = q + layer_params['bq']
        k = k + layer_params['bk']
        v = v + layer_params['bv']
    q = q.reshape(b, s, h, hd)
    k = k.reshape(b, s, kv, hd)
    v = v.reshape(b, s, kv, hd)
    q = apply_rope(q, angles)
    k = apply_rope(k, angles)
    if cache is not None and len(cache) == 4:
        # Decode-kernel path: this layer's cache rides through; the
        # step's k/v token is written first (single-element scatter)
        # and the pallas kernel attends over lengths+1 positions
        # including it. Returns the UPDATED layer cache as kv_out.
        from skypilot_tpu.ops import decode_attention as da
        k_l, v_l, lengths, rows = cache
        k_l = write_decode_token(k_l, k[:, 0], rows, lengths)
        v_l = write_decode_token(v_l, v[:, 0], rows, lengths)
        qg = q.reshape(b, kv, h // kv, hd)
        out = da.decode_attention(
            qg, k_l, v_l, lengths + 1,
            interpret=(cfg.attn_kernel == 'interpret'))
        if out is None:
            raise ValueError(
                'decode kernel enabled but the cache window does not '
                'block-tile; the engine should not have set '
                'attn_kernel for this max_decode_len')
        attn_out = out.reshape(b, s, h * hd)
        kv_out = (k_l, v_l)
    elif cache is not None:
        # Cache path: attend over previous tokens + this step's k/v
        # analytically; return only the fresh (k, v) token — the decode
        # skeleton owns the (tiny, in-place) cache write.
        k_cache, v_cache, lengths = cache
        attn_out = _cached_attention(q, k_cache, v_cache, k, v,
                                     lengths).reshape(b, s, h * hd)
        kv_out = (k, v)
    elif prefix is not None:
        # Extend path (prefix-KV reuse): suffix attends over the reused
        # prefix + itself; emits only the SUFFIX k/v (the engine
        # concatenates for the cache insert).
        attn_out = _extend_attention(q, prefix[0], prefix[1], k,
                                     v).reshape(b, s, h * hd)
        kv_out = (k, v)
    else:
        attn_out = attention(q, k, v, cfg).reshape(b, s, h * hd)
        kv_out = (k, v) if return_kv else None
    proj = quant.qdot(attn_out, layer_params['wo'], kernel=kern)
    if 'bo' in layer_params:      # HF Llama attention_bias o_proj bias
        proj = proj + layer_params['bo']
    x = x + proj
    return _shard(x, ACT_SPEC), kv_out


def forward(params: Params, tokens: jax.Array,
            cfg: LlamaConfig,
            positions: Optional[jax.Array] = None,
            return_kv: bool = False,
            prefix=None):
    """tokens [B, S] int32 -> logits [B, S, V] float32.

    `prefix={'k': [L, B, S_pre, KV, hd], 'v': ...}` (real tokens only)
    runs the extend-prefill path: `tokens` are a suffix whose
    `positions` the caller offsets by S_pre; attention sees
    prefix + suffix, and the returned kv covers the SUFFIX only."""
    b, s = tokens.shape
    if positions is None:
        positions = jnp.arange(s)
    angles = rope_frequencies(cfg, positions)
    x = _embed(params, tokens, cfg)
    x = _shard(x, ACT_SPEC)

    # Bind return_kv BEFORE any jax.checkpoint wrap: a bool passed through
    # remat at call time would be traced and crash the `if return_kv`.
    layer_fn = functools.partial(_layer, cfg, return_kv=return_kv)
    if cfg.remat and not return_kv:
        layer_fn = jax.checkpoint(
            layer_fn,
            policy=jax.checkpoint_policies.checkpoint_dots_with_no_batch_dims)

    kv = None
    if cfg.scan_layers:
        if prefix is not None:
            def scan_body(carry, xs):
                layer_params, k_pre, v_pre = xs
                return layer_fn(carry, layer_params, angles,
                                prefix=(k_pre, v_pre))
            x, kv = jax.lax.scan(
                scan_body, x, (params['layers'], prefix['k'],
                               prefix['v']))
        else:
            def scan_body(carry, layer_params):
                return layer_fn(carry, layer_params, angles)
            x, kv = jax.lax.scan(scan_body, x, params['layers'])
    else:
        ks, vs = [], []
        for i in range(cfg.n_layers):
            layer_params = jax.tree.map(lambda p: p[i], params['layers'])
            layer_prefix = (None if prefix is None else
                            (prefix['k'][i], prefix['v'][i]))
            x, layer_kv = layer_fn(x, layer_params, angles,
                                   prefix=layer_prefix)
            if return_kv:
                ks.append(layer_kv[0])
                vs.append(layer_kv[1])
        if return_kv:
            kv = (jnp.stack(ks), jnp.stack(vs))

    x = rms_norm(x, params['final_norm'], cfg.norm_eps)
    logits = quant.qeinsum('bsd,vd->bsv', x, params['lm_head'],
                           kernel=getattr(cfg, 'int8_kernel', None),
                           preferred_element_type=jnp.float32)
    logits = _shard(logits, LOGITS_SPEC)
    if return_kv:
        return logits, {'k': kv[0], 'v': kv[1]}
    return logits


# Decode path (KV cache) ---------------------------------------------- #
#
# Serving counterpart of the reference's JetStream recipe
# (reference examples/tpu/v6e/README.md:104-120): instead of shelling out
# to an external engine, the cache layout and the single-token decode step
# are in-framework. Layout:
#     cache = {'k': tuple(L x [B, KV, hd, T]), 'v': same}
# (T = max_decode_len), each layer leaf sharded KV_LAYER_SPEC (KV heads
# split over tp) — see the layout rationale comment above
# init_kv_cache. `lengths[b]` counts tokens already in slot b;
# attention masks the cache to t < lengths[b] and scores this step's
# fresh k/v as one extra analytic column (_cached_attention); the
# skeleton then writes the new token at index lengths[b] with a
# single-element scatter (decode_tail). Everything is static-shape so
# the decode step compiles once.

# The serving engine gates prefix-KV reuse on this (the extend path
# above); model modules without it (mixtral) prefill normally.
SUPPORTS_PREFIX = True

# Cache layout: ONE array per layer (a tuple pytree), each
# [B, KV, hd, T] — kv-head-major with T minor, NOT the model's
# [B, S, KV, hd] activation layout, for three measured reasons
# (r5 v5e traces, scripts/layout_probe*.py + profile_decode.py):
#   * T minor is lane-aligned for any T % 128 == 0 window. head_dim
#     minor at hd=64 < the 128-lane tile padded the RESIDENT cache to
#     2x its logical bytes and decode streams the whole cache every
#     step — layout alone halves cache traffic for hd-64 families.
#   * Per-layer arrays: a stacked [L, ...] cache made XLA materialize
#     a dynamic-slice copy of every layer's cache every decode step,
#     then relayout it for the score matmul ({4,2,3,1,0} ->
#     {3,4,2,1,0} copies — together ~36% of the step in the trace).
#     Separate arrays consumed directly by an unrolled layer loop
#     compile to copy-free reads (1.92 -> 1.41 ms/step at B=32,
#     T=256, 16 layers).
#   * It is the score matmul's native operand layout.
KV_LAYER_SPEC = P(('dp', 'fsdp'), 'tp', None, None)   # per-layer leaf
# Per-token scales of an int8 cache layer: [B, KV, T] (hd reduced).
KV_SCALE_SPEC = P(('dp', 'fsdp'), 'tp', None)


def init_kv_cache(cfg: LlamaConfig, batch_size: int, max_len: int,
                  quantized: bool = False) -> Params:
    """KV cache {'k': tuple(L x [B, KV, hd, T]), 'v': ...};
    `quantized` stores int8 values + per-(token, kv-head) fp32 scales
    (quant.QTensor leaves — a pytree, so jit/sharding plumbing is
    unchanged). Decode streams the whole cache every step, so int8
    halves its HBM traffic AND its residency (bigger decode batches
    in the same chip)."""
    shape = (batch_size, cfg.n_kv_heads, cfg.head_dim, max_len)
    if quantized:
        scale_shape = shape[:2] + (max_len,)      # [B, KV, T]
        def leaf():
            return quant.QTensor(
                q=_shard(jnp.zeros(shape, jnp.int8), KV_LAYER_SPEC),
                scale=_shard(jnp.zeros(scale_shape, jnp.float32),
                             KV_SCALE_SPEC))
    else:
        def leaf():
            return _shard(jnp.zeros(shape, cfg.dtype), KV_LAYER_SPEC)
    return {'k': tuple(leaf() for _ in range(cfg.n_layers)),
            'v': tuple(leaf() for _ in range(cfg.n_layers))}


def kv_cache_specs(quantized: bool = False, n_layers: int = 1) -> Params:
    """PartitionSpec tree matching init_kv_cache's structure (the
    engine's out_shardings need the QTensor sub-structure too)."""
    if quantized:
        def leaf():
            return quant.QTensor(q=KV_LAYER_SPEC, scale=KV_SCALE_SPEC)
    else:
        def leaf():
            return KV_LAYER_SPEC
    return {'k': tuple(leaf() for _ in range(n_layers)),
            'v': tuple(leaf() for _ in range(n_layers))}


def quantize_kv(x: jax.Array) -> 'quant.QTensor':
    """Per-(token, head) symmetric int8 over head_dim (x [..., hd])."""
    return quant.quantize(x, reduce_axes=(-1,))


def _dense_kv(x) -> jax.Array:
    """Dense view of a (possibly int8) cache slice [.., KV, hd, T]
    (scale [.., KV, T] — head_dim is axis -2); the int8->bf16 convert
    + scale fuse into the consuming attention matmul the same way
    weight dequant does in quant.qdot."""
    if isinstance(x, quant.QTensor):
        return quant.dequantize(x, reduce_axes=(-2,))
    return x


def write_decode_token(cache_leaf, new, rows, lengths):
    """Scatter one step's fresh [B, KV, hd] k or v token into one
    layer's [B, KV, hd, T] cache at T position lengths[b] — int8
    caches quantize per (token, head) at write time. rows/lengths are
    separated by basic slices, so numpy advanced-indexing moves the
    [B] dims to the front: the target region is [B, KV(, hd)],
    matching the token's shape."""
    if isinstance(cache_leaf, quant.QTensor):
        qt = quantize_kv(new)
        return quant.QTensor(
            q=cache_leaf.q.at[rows, :, :, lengths].set(qt.q),
            scale=cache_leaf.scale.at[rows, :, lengths].set(qt.scale))
    return cache_leaf.at[rows, :, :, lengths].set(
        new.astype(cache_leaf.dtype))


def _cached_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                      k_new: jax.Array, v_new: jax.Array,
                      lengths: jax.Array) -> jax.Array:
    """q [B,1,H,hd]; k/v_cache [B,KV,hd,T] (cache layout — see
    the comment above init_kv_cache) hold ONLY previous tokens (positions
    t < lengths[b]); k/v_new [B,1,KV,hd] are this step's fresh k/v,
    handled as one extra score column instead of being scattered into
    the cache first. This keeps the decode step's cache traffic
    read-only inside the layer — the skeleton (decode_tail) writes the
    single new token column afterwards, so a step never copies the
    full cache (HBM write traffic per step drops from O(cache) to
    O(B*KV*hd) per layer). This is the CPU/mesh fallback; single-chip
    TPU decode routes through ops/decode_attention.py instead."""
    k_cache = _dense_kv(k_cache)   # int8 cache: dequant fuses into the
    v_cache = _dense_kv(v_cache)   # einsum reads (weights-style)
    b, _, h, hd = q.shape
    kv_heads = k_cache.shape[1]
    t = k_cache.shape[3]
    group = h // kv_heads
    q = q.reshape(b, kv_heads, group, hd)
    scores = jnp.einsum('bkgh,bkht->bkgt', q, k_cache,
                        preferred_element_type=jnp.float32)
    score_new = jnp.einsum('bkgh,bskh->bkgs', q, k_new,
                           preferred_element_type=jnp.float32)   # s == 1
    scale = jnp.sqrt(hd).astype(jnp.float32)
    mask = jnp.arange(t)[None] < lengths[:, None]           # [B, T]
    scores = jnp.where(mask[:, None, None], scores / scale, -1e30)
    allscores = jnp.concatenate([scores, score_new / scale], axis=-1)
    probs = jax.nn.softmax(allscores, axis=-1)              # [B,KV,G,T+1]
    out = (jnp.einsum('bkgt,bkht->bkgh',
                      probs[..., :t].astype(v_cache.dtype), v_cache)
           + jnp.einsum('bkgs,bskh->bkgh',
                        probs[..., t:].astype(v_new.dtype), v_new))
    return out.reshape(b, 1, h, hd)


def decode_tail(params: Params, cache: Params, lengths: jax.Array,
                tokens: jax.Array, cfg: LlamaConfig, layer_body):
    """Shared decode-step skeleton (Llama + the MoE models): embed the
    new token, run `layer_body` over the layers (unrolled), final-norm
    + lm_head. `layer_body(x, layer_params, angles, (k_cache_layer,
    v_cache_layer, lengths))` attends with the new token handled
    analytically and returns (x, (k_new, v_new)) — just this step's
    [B,1,KV,hd] token.

    The cache is a TUPLE of per-layer [B,KV,hd,T] arrays consumed by
    an unrolled layer loop: each layer's cache is read exactly once
    (the attention must) and written with a single-element scatter —
    never sliced out of a stacked array or copied. The two previous
    designs both measured far off the v5e HBM roofline: cache as scan
    ys re-materialized the whole cache every step (~32% of roofline),
    and a stacked [L,...] scan carry made XLA materialize + relayout
    every layer's slice (~36% of the step — see the KV layout comment
    above init_kv_cache)."""
    angles = jax.vmap(
        lambda p: rope_frequencies(cfg, p[None]))(lengths)    # [B,1,half]

    x = _embed(params, tokens, cfg)[:, None]              # [B,1,D]
    rows = jnp.arange(tokens.shape[0])
    use_kernel = getattr(cfg, 'attn_kernel', None) is not None

    new_k, new_v = list(cache['k']), list(cache['v'])
    for i in range(cfg.n_layers):
        layer_params = jax.tree.map(lambda p: p[i], params['layers'])
        if use_kernel:
            # Kernel path: the layer cache flows INTO the layer; the
            # attention block writes the token and returns it updated.
            x, (new_k[i], new_v[i]) = layer_body(
                x, layer_params, angles,
                (new_k[i], new_v[i], lengths, rows))
        else:
            x, (nk, nv) = layer_body(x, layer_params, angles,
                                     (new_k[i], new_v[i], lengths))
            new_k[i] = write_decode_token(new_k[i], nk[:, 0], rows,
                                          lengths)
            new_v[i] = write_decode_token(new_v[i], nv[:, 0], rows,
                                          lengths)
    new_k, new_v = tuple(new_k), tuple(new_v)
    x = rms_norm(x, params['final_norm'], cfg.norm_eps)
    logits = quant.qeinsum('bsd,vd->bsv', x, params['lm_head'],
                           kernel=getattr(cfg, 'int8_kernel', None),
                           preferred_element_type=jnp.float32)
    return logits[:, 0], {'k': new_k, 'v': new_v}


def decode_step(params: Params, cache: Params, lengths: jax.Array,
                tokens: jax.Array, cfg: LlamaConfig):
    """One token for every slot. tokens [B] int32, lengths [B] = #tokens
    already cached per slot. Returns (logits [B, V] fp32, new_cache)."""
    def layer_body(x, layer_params, angles, cache_triple):
        return _layer(cfg, x, layer_params, angles, cache=cache_triple)

    return decode_tail(params, cache, lengths, tokens, cfg, layer_body)
