"""Gemma family — expressed on the shared Llama-lineage engine.

The reference serves/fine-tunes Gemma via external recipes (reference
llm/gemma/README.md shells out to vLLM/HF); here Gemma is the same
in-framework model as Llama/Qwen2 (models/llama.py) with its four
architectural deltas expressed as config knobs + load-time folding:

  * explicit head_dim (gemma-7b: 16 heads x 256 > dim 3072) —
    LlamaConfig.head_dim_override;
  * GELU(tanh) MLP instead of SiLU — mlp_act='gelu_tanh';
  * input embeddings scaled by sqrt(dim) — embed_scale;
  * RMSNorm multiplies by (1 + w) — folded into the stored norm
    weights at conversion (models/hf_convert.from_hf_gemma), so the
    runtime norm stays the shared llama.rms_norm;
  * lm_head tied to the embedding (always, both sizes).

Everything else — KV-cache serving engine, int8 weight/KV quant,
tensor-parallel shardings, trainer — is inherited unchanged.
"""
from __future__ import annotations

import math

from skypilot_tpu.models.llama import (    # noqa: F401 — re-exports:
    LlamaConfig, decode_step, forward, init_kv_cache, init_params,
    kv_cache_specs, param_shardings, quantize_params,
    quantized_param_shardings)


def gemma_7b() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=256000, dim=3072, n_layers=28, n_heads=16,
        n_kv_heads=16, head_dim_override=256, ffn_dim=24576,
        max_seq_len=8192, rope_theta=10000.0, norm_eps=1e-6,
        mlp_act='gelu_tanh', embed_scale=math.sqrt(3072.0),
        tied_embeddings=True)


def gemma_2b() -> LlamaConfig:
    return LlamaConfig(
        vocab_size=256000, dim=2048, n_layers=18, n_heads=8,
        n_kv_heads=1, head_dim_override=256, ffn_dim=16384,
        max_seq_len=8192, rope_theta=10000.0, norm_eps=1e-6,
        mlp_act='gelu_tanh', embed_scale=math.sqrt(2048.0),
        tied_embeddings=True)


def gemma_tiny() -> LlamaConfig:
    """Structure-preserving toy config (incl. head_dim != dim/heads and
    MQA) for tests / compile checks."""
    return LlamaConfig(
        vocab_size=512, dim=96, n_layers=2, n_heads=4, n_kv_heads=1,
        head_dim_override=32, ffn_dim=256, max_seq_len=512,
        rope_theta=10000.0, norm_eps=1e-6, mlp_act='gelu_tanh',
        embed_scale=math.sqrt(96.0), tied_embeddings=True,
        use_flash_attention=False)
