"""ResNet in Flax, TPU-first — the vision model family.

The reference's ResNet story is recipe-level torch DDP
(examples/resnet_distributed_torch.yaml: torchrun over SKYPILOT_NODE_*
env). Here it is an in-framework model: convolutions are MXU work under
XLA (lax.conv lowers to the systolic array in bf16), the batch is sharded
over ('dp','fsdp') with one `with_sharding_constraint`, and cross-host
gradient reduction is XLA's — no DDP wrapper, no NCCL.

BatchNorm runs in its functional Flax form: batch statistics live in a
`batch_stats` collection threaded through the train step; XLA turns the
per-batch mean/var into cross-replica psums automatically because the
batch axis is sharded (equivalent to torch's SyncBatchNorm, for free).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Callable, Dict, Sequence, Tuple

import flax.linen as nn
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from skypilot_tpu.parallel.mesh import shard as _shard

BATCH_SPEC = P(('dp', 'fsdp'), None, None, None)   # [B, H, W, C]


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    stage_sizes: Sequence[int] = (3, 4, 6, 3)      # ResNet-50
    num_filters: int = 64
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16

    @property
    def name(self) -> str:
        blocks = {(2, 2, 2, 2): 18, (3, 4, 6, 3): 50,
                  (3, 4, 23, 3): 101, (3, 8, 36, 3): 152}
        n = blocks.get(tuple(self.stage_sizes))
        return f'ResNet-{n}' if n else 'ResNet-custom'


def resnet50(num_classes: int = 1000) -> ResNetConfig:
    return ResNetConfig(num_classes=num_classes)


def resnet18(num_classes: int = 1000) -> ResNetConfig:
    return ResNetConfig(stage_sizes=(2, 2, 2, 2), num_classes=num_classes)


def resnet_tiny(num_classes: int = 10) -> ResNetConfig:
    """Structure-preserving toy config for tests."""
    return ResNetConfig(stage_sizes=(1, 1), num_filters=8,
                        num_classes=num_classes)


class BottleneckBlock(nn.Module):
    filters: int
    strides: int
    dtype: Any

    @nn.compact
    def __call__(self, x, train: bool):
        norm = functools.partial(nn.BatchNorm, use_running_average=not train,
                                 momentum=0.9, dtype=self.dtype)
        conv = functools.partial(nn.Conv, use_bias=False, dtype=self.dtype)
        residual = x
        y = conv(self.filters, (1, 1))(x)
        y = nn.relu(norm()(y))
        y = conv(self.filters, (3, 3), (self.strides, self.strides))(y)
        y = nn.relu(norm()(y))
        y = conv(self.filters * 4, (1, 1))(y)
        y = norm(scale_init=nn.initializers.zeros)(y)
        if residual.shape != y.shape:
            residual = conv(self.filters * 4, (1, 1),
                            (self.strides, self.strides))(residual)
            residual = norm()(residual)
        return nn.relu(y + residual)


class ResNet(nn.Module):
    cfg: ResNetConfig

    @nn.compact
    def __call__(self, x, train: bool = True):
        cfg = self.cfg
        x = x.astype(cfg.dtype)
        x = nn.Conv(cfg.num_filters, (7, 7), (2, 2), use_bias=False,
                    dtype=cfg.dtype)(x)
        x = nn.relu(nn.BatchNorm(use_running_average=not train,
                                 momentum=0.9, dtype=cfg.dtype)(x))
        x = nn.max_pool(x, (3, 3), (2, 2), padding='SAME')
        for i, block_count in enumerate(cfg.stage_sizes):
            for j in range(block_count):
                strides = 2 if i > 0 and j == 0 else 1
                x = BottleneckBlock(cfg.num_filters * 2 ** i, strides,
                                    cfg.dtype)(x, train)
        x = jnp.mean(x, axis=(1, 2))
        # Head in fp32: logits feed a softmax.
        return nn.Dense(cfg.num_classes, dtype=jnp.float32)(x)


TrainStateResnet = Dict[str, Any]   # {'params', 'batch_stats', 'opt_state', 'step'}


def init_train_state(cfg: ResNetConfig, mesh: Mesh,
                     optimizer: optax.GradientTransformation = None,
                     image_size: int = 224, seed: int = 0
                     ) -> Tuple[TrainStateResnet, Any, Any]:
    """Returns (state, model, optimizer). Params replicate (a ResNet is
    ~25M params — sharding them buys nothing); the batch shards."""
    optimizer = optimizer or optax.sgd(0.1, momentum=0.9, nesterov=True)
    model = ResNet(cfg)
    dummy = jnp.zeros((2, image_size, image_size, 3), jnp.float32)
    variables = model.init(jax.random.PRNGKey(seed), dummy, train=True)
    state = {
        'step': jnp.zeros((), jnp.int32),
        'params': variables['params'],
        'batch_stats': variables['batch_stats'],
        'opt_state': optimizer.init(variables['params']),
    }
    replicated = NamedSharding(mesh, P())
    state = jax.device_put(state, replicated)
    return state, model, optimizer


def make_train_step(model: ResNet, mesh: Mesh,
                    optimizer: optax.GradientTransformation
                    ) -> Callable:
    """Jitted SPMD step over batch = {'images': [B,H,W,C], 'labels': [B]}.
    The only parallelism annotation is the batch sharding — XLA derives
    the gradient all-reduce and the cross-replica BN statistics."""
    batch_shardings = {
        'images': NamedSharding(mesh, BATCH_SPEC),
        'labels': NamedSharding(mesh, P(('dp', 'fsdp'))),
    }
    replicated = NamedSharding(mesh, P())

    def loss_fn(params, batch_stats, images, labels):
        logits, mutated = model.apply(
            {'params': params, 'batch_stats': batch_stats}, images,
            train=True, mutable=['batch_stats'])
        one_hot = jax.nn.one_hot(labels, logits.shape[-1])
        loss = optax.softmax_cross_entropy(logits, one_hot).mean()
        return loss, (mutated['batch_stats'], logits)

    def step_fn(state, batch):
        images = _shard(batch['images'], BATCH_SPEC)
        (loss, (new_stats, logits)), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(state['params'], state['batch_stats'],
                                   images, batch['labels'])
        updates, new_opt = optimizer.update(grads, state['opt_state'],
                                            state['params'])
        new_params = optax.apply_updates(state['params'], updates)
        acc = jnp.mean(
            (jnp.argmax(logits, -1) == batch['labels']).astype(jnp.float32))
        new_state = {'step': state['step'] + 1, 'params': new_params,
                     'batch_stats': new_stats, 'opt_state': new_opt}
        return new_state, {'loss': loss, 'accuracy': acc}

    return jax.jit(step_fn,
                   in_shardings=(replicated, batch_shardings),
                   out_shardings=(replicated, None),
                   donate_argnums=(0,))
