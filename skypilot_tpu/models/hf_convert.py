"""HuggingFace checkpoint conversion: LlamaForCausalLM -> our params.

The reference consumes HF checkpoints by shelling out to torch
(reference llm/llama-3_1-finetuning/lora.yaml, examples/tpu/v6e/
train-llama3-8b.yaml run HF `run_clm`/torchrun on the checkpoint); here
the weights load directly into the functional JAX model, so the same
Llama checkpoint trains (train/trainer.py), serves (serve/engine.py,
incl. int8 + tensor-parallel), and checkpoints (orbax) in-framework.

Conventions verified against transformers' modeling_llama:
  * torch Linear stores [out, in] -> our right-multiply mats transpose;
  * RoPE is the half-split rotate_half form — exactly models/llama.py
    apply_rope, so NO head-dim permutation of q/k weights is needed;
  * RMSNorm multiplies the weight after normalization (same as
    llama.rms_norm);
  * tied embeddings (tie_word_embeddings) reuse embed as lm_head.
"""
from __future__ import annotations

from typing import Any, Tuple

import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import llama


def _rope_scaling_from_hf(hf_config: Any):
    """Map hf rope_scaling to llama.RopeScaling; raise on schemes we do
    not implement (silently dropping one would give wrong logits for
    every position — Llama-3.1/3.2 checkpoints all ship
    rope_type='llama3')."""
    rs = getattr(hf_config, 'rope_scaling', None)
    if rs is None:
        return None
    rope_type = rs.get('rope_type', rs.get('type', 'default'))
    if rope_type == 'default':
        return None
    if rope_type != 'llama3':
        raise NotImplementedError(
            f'rope_scaling rope_type={rope_type!r} is not supported '
            "(implemented: 'llama3', 'default')")
    return llama.RopeScaling(
        factor=float(rs['factor']),
        low_freq_factor=float(rs['low_freq_factor']),
        high_freq_factor=float(rs['high_freq_factor']),
        original_max_position_embeddings=int(
            rs['original_max_position_embeddings']))


def config_from_hf(hf_config: Any, dtype: Any = jnp.bfloat16,
                   **overrides) -> llama.LlamaConfig:
    """LlamaConfig from a transformers LlamaConfig."""
    kw = dict(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        ffn_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(hf_config.rope_theta),
        norm_eps=float(hf_config.rms_norm_eps),
        rope_scaling=_rope_scaling_from_hf(hf_config),
        dtype=dtype,
    )
    kw.update(overrides)
    return llama.LlamaConfig(**kw)


def from_hf_llama(hf_model: Any, dtype: Any = jnp.bfloat16,
                  **config_overrides
                  ) -> Tuple[llama.LlamaConfig, llama.Params]:
    """Convert a transformers LlamaForCausalLM (torch) to
    (LlamaConfig, params). `config_overrides` tweak the resulting
    config (e.g. use_flash_attention=False for CPU tests)."""
    cfg = config_from_hf(hf_model.config, dtype=dtype,
                         **config_overrides)
    sd = hf_model.state_dict()

    def arr(key: str, transpose: bool = False) -> np.ndarray:
        w = sd[key].detach().to('cpu').float().numpy()
        return w.T if transpose else w

    def stack(fmt: str, transpose: bool = False) -> jnp.ndarray:
        return jnp.asarray(
            np.stack([arr(fmt.format(i), transpose)
                      for i in range(cfg.n_layers)])).astype(dtype)

    embed = jnp.asarray(arr('model.embed_tokens.weight')).astype(dtype)
    if getattr(hf_model.config, 'tie_word_embeddings', False):
        lm_head = embed
    else:
        lm_head = jnp.asarray(arr('lm_head.weight')).astype(dtype)

    params = {
        'embed': embed,
        'layers': {
            'wq': stack('model.layers.{}.self_attn.q_proj.weight',
                        transpose=True),
            'wk': stack('model.layers.{}.self_attn.k_proj.weight',
                        transpose=True),
            'wv': stack('model.layers.{}.self_attn.v_proj.weight',
                        transpose=True),
            'wo': stack('model.layers.{}.self_attn.o_proj.weight',
                        transpose=True),
            'w_gate': stack('model.layers.{}.mlp.gate_proj.weight',
                            transpose=True),
            'w_up': stack('model.layers.{}.mlp.up_proj.weight',
                          transpose=True),
            'w_down': stack('model.layers.{}.mlp.down_proj.weight',
                            transpose=True),
            'ln_attn': stack('model.layers.{}.input_layernorm.weight'),
            'ln_mlp': stack(
                'model.layers.{}.post_attention_layernorm.weight'),
        },
        'final_norm': jnp.asarray(arr('model.norm.weight')).astype(dtype),
        'lm_head': lm_head,
    }
    return cfg, params
