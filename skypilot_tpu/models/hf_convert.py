"""HuggingFace checkpoint conversion: LlamaForCausalLM -> our params.

The reference consumes HF checkpoints by shelling out to torch
(reference llm/llama-3_1-finetuning/lora.yaml, examples/tpu/v6e/
train-llama3-8b.yaml run HF `run_clm`/torchrun on the checkpoint); here
the weights load directly into the functional JAX model, so the same
Llama checkpoint trains (train/trainer.py), serves (serve/engine.py,
incl. int8 + tensor-parallel), and checkpoints (orbax) in-framework.

Conventions verified against transformers' modeling_llama:
  * torch Linear stores [out, in] -> our right-multiply mats transpose;
  * RoPE is the half-split rotate_half form — exactly models/llama.py
    apply_rope, so NO head-dim permutation of q/k weights is needed;
  * RMSNorm multiplies the weight after normalization (same as
    llama.rms_norm);
  * tied embeddings (tie_word_embeddings) reuse embed as lm_head.
"""
from __future__ import annotations

import functools
from typing import Any, Tuple

import jax.numpy as jnp
import numpy as np

from skypilot_tpu.models import llama
from skypilot_tpu.models import mixtral


def _rope_scaling_from_hf(hf_config: Any):
    """Map hf rope_scaling to llama.RopeScaling; raise on schemes we do
    not implement (silently dropping one would give wrong logits for
    every position — Llama-3.1/3.2 checkpoints all ship
    rope_type='llama3')."""
    rs = getattr(hf_config, 'rope_scaling', None)
    if rs is None:
        return None
    rope_type = rs.get('rope_type', rs.get('type', 'default'))
    if rope_type == 'default':
        return None
    if rope_type != 'llama3':
        raise NotImplementedError(
            f'rope_scaling rope_type={rope_type!r} is not supported '
            "(implemented: 'llama3', 'default')")
    return llama.RopeScaling(
        factor=float(rs['factor']),
        low_freq_factor=float(rs['low_freq_factor']),
        high_freq_factor=float(rs['high_freq_factor']),
        original_max_position_embeddings=int(
            rs['original_max_position_embeddings']))


def config_from_hf(hf_config: Any, dtype: Any = jnp.bfloat16,
                   **overrides) -> llama.LlamaConfig:
    """LlamaConfig from a transformers Llama/Qwen2 config. Qwen2
    ALWAYS carries q/k/v biases (Qwen2Attention hardcodes them —
    a stray 'attention_bias: false' in a re-uploaded config.json must
    not drop real weights); HF Llama's attention_bias additionally
    biases o_proj."""
    is_qwen2 = hf_config.model_type == 'qwen2'
    declared = bool(getattr(hf_config, 'attention_bias', False))
    attn_bias = is_qwen2 or declared
    kw = dict(
        vocab_size=hf_config.vocab_size,
        dim=hf_config.hidden_size,
        n_layers=hf_config.num_hidden_layers,
        n_heads=hf_config.num_attention_heads,
        n_kv_heads=hf_config.num_key_value_heads,
        ffn_dim=hf_config.intermediate_size,
        max_seq_len=hf_config.max_position_embeddings,
        rope_theta=float(hf_config.rope_theta),
        norm_eps=float(hf_config.rms_norm_eps),
        rope_scaling=_rope_scaling_from_hf(hf_config),
        attention_bias=attn_bias,
        attention_out_bias=declared and not is_qwen2,
        tied_embeddings=bool(getattr(hf_config, 'tie_word_embeddings',
                                     False)),
        dtype=dtype,
    )
    kw.update(overrides)
    return llama.LlamaConfig(**kw)


def _check_supported(hcfg: Any) -> None:
    """Raise on config features we would otherwise silently drop
    (same convention as _rope_scaling_from_hf: wrong-logits bugs must
    be loud). Attention biases ARE modeled: q/k/v for the Qwen2 family
    and HF-Llama attention_bias checkpoints, o_proj for the latter
    (LlamaConfig.attention_bias / .attention_out_bias)."""
    if getattr(hcfg, 'sliding_window', None) and getattr(
            hcfg, 'use_sliding_window', True):
        raise NotImplementedError(
            f'sliding_window={hcfg.sliding_window} is not supported '
            '(attention is global-causal)')


def _arr(sd: Any, key: str, transpose: bool = False) -> np.ndarray:
    """torch tensor -> HOST numpy (fp32). Staying on host matters: the
    engine device_puts these straight into their sharded layout, so a
    model that only fits sharded never materializes on one chip."""
    w = sd[key].detach().to('cpu').float().numpy()
    return w.T if transpose else w


def _stack(sd: Any, n_layers: int, dtype: Any, fmt: str,
           transpose: bool = False) -> np.ndarray:
    return np.stack([_arr(sd, fmt.format(i), transpose)
                     for i in range(n_layers)]).astype(dtype)


def _attention_and_norms(sd: Any, n_layers: int, dtype: Any,
                         attention_bias: bool = False,
                         attention_out_bias: bool = False):
    """The layer leaves Llama/Qwen2 and Mixtral share (attention +
    norms; q/k/v and o biases when the family has them)."""
    stack = functools.partial(_stack, sd, n_layers, dtype)
    out = {
        'wq': stack('model.layers.{}.self_attn.q_proj.weight',
                    transpose=True),
        'wk': stack('model.layers.{}.self_attn.k_proj.weight',
                    transpose=True),
        'wv': stack('model.layers.{}.self_attn.v_proj.weight',
                    transpose=True),
        'wo': stack('model.layers.{}.self_attn.o_proj.weight',
                    transpose=True),
        'ln_attn': stack('model.layers.{}.input_layernorm.weight'),
        'ln_mlp': stack(
            'model.layers.{}.post_attention_layernorm.weight'),
    }
    if attention_bias:
        out.update({
            'bq': stack('model.layers.{}.self_attn.q_proj.bias'),
            'bk': stack('model.layers.{}.self_attn.k_proj.bias'),
            'bv': stack('model.layers.{}.self_attn.v_proj.bias'),
        })
    if attention_out_bias:
        out['bo'] = stack('model.layers.{}.self_attn.o_proj.bias')
    return out


def _embed_and_lm_head(sd: Any, hcfg: Any, dtype: Any):
    embed = _arr(sd, 'model.embed_tokens.weight').astype(dtype)
    if getattr(hcfg, 'tie_word_embeddings', False):
        lm_head = embed
    else:
        lm_head = _arr(sd, 'lm_head.weight').astype(dtype)
    return embed, lm_head


def _dense_mlp(stack) -> dict:
    """gate/up/down leaves shared by the dense-FFN families
    (Llama/Qwen2/Gemma)."""
    return {
        'w_gate': stack('model.layers.{}.mlp.gate_proj.weight',
                        transpose=True),
        'w_up': stack('model.layers.{}.mlp.up_proj.weight',
                      transpose=True),
        'w_down': stack('model.layers.{}.mlp.down_proj.weight',
                        transpose=True),
    }


def from_hf_llama(hf_model: Any, dtype: Any = jnp.bfloat16,
                  **config_overrides
                  ) -> Tuple[llama.LlamaConfig, llama.Params]:
    """Convert a transformers LlamaForCausalLM OR Qwen2ForCausalLM
    (torch) to (LlamaConfig, params) — Qwen2 is the Llama architecture
    plus q/k/v biases. `config_overrides` tweak the resulting config
    (e.g. use_flash_attention=False for CPU tests). Params are HOST
    numpy arrays (see _arr)."""
    _check_supported(hf_model.config)
    cfg = config_from_hf(hf_model.config, dtype=dtype,
                         **config_overrides)
    sd = hf_model.state_dict()
    stack = functools.partial(_stack, sd, cfg.n_layers, dtype)
    embed, lm_head = _embed_and_lm_head(sd, hf_model.config, dtype)

    layers = _attention_and_norms(
        sd, cfg.n_layers, dtype, attention_bias=cfg.attention_bias,
        attention_out_bias=cfg.attention_out_bias)
    layers.update(_dense_mlp(stack))
    params = {
        'embed': embed,
        'layers': layers,
        'final_norm': _arr(sd, 'model.norm.weight').astype(dtype),
        'lm_head': lm_head,
    }
    return cfg, params


def from_hf_gemma(hf_model: Any, dtype: Any = jnp.bfloat16,
                  **config_overrides
                  ) -> Tuple[llama.LlamaConfig, llama.Params]:
    """Convert a transformers GemmaForCausalLM to (LlamaConfig, params)
    on the shared Llama-lineage engine (models/gemma.py): explicit
    head_dim, gelu_tanh MLP, sqrt(dim) embedding scale, and Gemma's
    (1 + w) RMSNorm FOLDED into the stored norm weights so the runtime
    norm is the shared llama.rms_norm. lm_head is always tied."""
    hcfg = hf_model.config
    _check_supported(hcfg)
    act = getattr(hcfg, 'hidden_activation', None) or getattr(
        hcfg, 'hidden_act', 'gelu_pytorch_tanh')
    if act not in ('gelu', 'gelu_pytorch_tanh'):
        raise NotImplementedError(
            f'Gemma hidden activation {act!r} is not supported')
    # Loud on anything we would silently drop (the module convention):
    # stock Gemma has none of these, but re-uploaded fine-tunes can.
    if getattr(hcfg, 'attention_bias', False):
        raise NotImplementedError(
            'Gemma checkpoints with attention_bias=True are not '
            'supported (bias weights would be dropped)')
    if not getattr(hcfg, 'tie_word_embeddings', True):
        raise NotImplementedError(
            'Gemma checkpoints with untied lm_head are not supported '
            '(the separate lm_head.weight would be dropped)')
    if _rope_scaling_from_hf(hcfg) is not None:
        raise NotImplementedError(
            'Gemma checkpoints with rope_scaling are not supported')
    import math
    kw = dict(
        vocab_size=hcfg.vocab_size,
        dim=hcfg.hidden_size,
        n_layers=hcfg.num_hidden_layers,
        n_heads=hcfg.num_attention_heads,
        n_kv_heads=hcfg.num_key_value_heads,
        head_dim_override=hcfg.head_dim,
        ffn_dim=hcfg.intermediate_size,
        max_seq_len=hcfg.max_position_embeddings,
        rope_theta=float(hcfg.rope_theta),
        norm_eps=float(hcfg.rms_norm_eps),
        mlp_act='gelu_tanh',
        embed_scale=math.sqrt(float(hcfg.hidden_size)),
        tied_embeddings=True,
        dtype=dtype,
    )
    kw.update(config_overrides)
    cfg = llama.LlamaConfig(**kw)
    sd = hf_model.state_dict()
    stack = functools.partial(_stack, sd, cfg.n_layers, dtype)
    embed = _arr(sd, 'model.embed_tokens.weight').astype(dtype)

    layers = _attention_and_norms(sd, cfg.n_layers, dtype)
    # (1 + w) -> stored as w + 1 (fp32 add before the dtype cast).
    for name in ('ln_attn', 'ln_mlp'):
        layers[name] = (layers[name].astype(np.float32)
                        + 1.0).astype(dtype)
    layers.update(_dense_mlp(stack))
    final_norm = (_arr(sd, 'model.norm.weight').astype(np.float32)
                  + 1.0).astype(dtype)
    params = {
        'embed': embed,
        'layers': layers,
        'final_norm': final_norm,
        'lm_head': embed,        # always tied
    }
    return cfg, params


def from_hf_mixtral(hf_model: Any, dtype: Any = jnp.bfloat16,
                    **config_overrides
                    ) -> Tuple[mixtral.MixtralConfig, mixtral.Params]:
    """Convert a transformers MixtralForCausalLM to
    (MixtralConfig, params). HF stores experts as per-expert Linears
    (w1=gate [F,D], w2=down [D,F], w3=up [F,D]); ours are stacked
    [L, E, D, F] batched matmuls for the one-hot dispatch formulation
    (ops/moe.py). Routing semantics line up (softmax -> top-k ->
    renormalize); HF's gather routing never drops tokens, which our
    serving paths match via the drop-free capacity pin."""
    hcfg = hf_model.config
    kw = dict(
        vocab_size=hcfg.vocab_size,
        dim=hcfg.hidden_size,
        n_layers=hcfg.num_hidden_layers,
        n_heads=hcfg.num_attention_heads,
        n_kv_heads=hcfg.num_key_value_heads,
        ffn_dim=hcfg.intermediate_size,
        num_experts=hcfg.num_local_experts,
        top_k=hcfg.num_experts_per_tok,
        max_seq_len=hcfg.max_position_embeddings,
        rope_theta=float(hcfg.rope_theta),
        norm_eps=float(hcfg.rms_norm_eps),
        dtype=dtype,
    )
    kw.update(config_overrides)
    _check_supported(hcfg)
    cfg = mixtral.MixtralConfig(**kw)
    sd = hf_model.state_dict()

    def stack_experts(which: str) -> np.ndarray:
        """[L, E, D, F] (gate/up) or [L, E, F, D] (down) from per-expert
        Linears, transposed from torch's [out, in]."""
        return np.stack([
            np.stack([_arr(sd, f'model.layers.{i}.block_sparse_moe.'
                           f'experts.{e}.{which}.weight', transpose=True)
                      for e in range(cfg.num_experts)])
            for i in range(cfg.n_layers)]).astype(dtype)

    embed, lm_head = _embed_and_lm_head(sd, hcfg, dtype)
    layers = _attention_and_norms(sd, cfg.n_layers, dtype)
    layers.update({
        # Router stays fp32 (models/mixtral.py init convention).
        'w_router': np.stack(
            [_arr(sd, f'model.layers.{i}.block_sparse_moe.gate.weight',
                  transpose=True)
             for i in range(cfg.n_layers)]).astype(np.float32),
        'w_gate': stack_experts('w1'),
        'w_up': stack_experts('w3'),
        'w_down': stack_experts('w2'),
    })
    params = {
        'embed': embed,
        'layers': layers,
        'final_norm': _arr(sd, 'model.norm.weight').astype(dtype),
        'lm_head': lm_head,
    }
    return cfg, params


def from_hf_auto(path: str, dtype: Any = jnp.bfloat16,
                 **config_overrides):
    """Load + convert a checkpoint directory by model_type. Returns
    (model_module, cfg, params, eos_id) with the torch model freed
    before returning (peak host memory = torch weights OR numpy weights,
    not both held alive by the caller). eos_id is an int, a tuple (HF
    lists several for Llama-3.1), or None. The single shared loader for
    the serving and training entry points."""
    import transformers

    model_type = transformers.AutoConfig.from_pretrained(path).model_type
    if model_type == 'mixtral':
        hf = transformers.MixtralForCausalLM.from_pretrained(
            path, torch_dtype='auto', low_cpu_mem_usage=True)
        from skypilot_tpu.models import mixtral as model_module
        cfg, params = from_hf_mixtral(hf, dtype=dtype,
                                      **config_overrides)
    elif model_type in ('llama', 'qwen2'):
        loader = (transformers.LlamaForCausalLM if model_type == 'llama'
                  else transformers.Qwen2ForCausalLM)
        hf = loader.from_pretrained(
            path, torch_dtype='auto', low_cpu_mem_usage=True)
        from skypilot_tpu.models import llama as model_module
        cfg, params = from_hf_llama(hf, dtype=dtype, **config_overrides)
    elif model_type == 'gemma':
        hf = transformers.GemmaForCausalLM.from_pretrained(
            path, torch_dtype='auto', low_cpu_mem_usage=True)
        from skypilot_tpu.models import llama as model_module
        cfg, params = from_hf_gemma(hf, dtype=dtype, **config_overrides)
    else:
        raise ValueError(
            f'unsupported HF model_type {model_type!r} '
            "(supported: 'llama', 'qwen2', 'gemma', 'mixtral')")
    eos = hf.config.eos_token_id
    del hf
    if isinstance(eos, (list, tuple)):
        eos = tuple(eos)
    elif eos is not None:
        eos = int(eos)
    return model_module, cfg, params, eos
