"""Optimizer: pick the cheapest (or fastest-to-acquire) feasible offering
per task.

Reference equivalent: sky/optimizer.py (1345 LoC: DP over chains at :411, ILP
via pulp for general DAGs at :472, parent->child egress model at :77-108).
Per-task minimization is exact for independent tasks; tasks coupled by
data-bearing edges get a JOINT region assignment (exhaustive over the
data-connected tasks — exact, like the reference's ILP, at the DAG sizes
tasks actually have; CBC is not in this image) with a greedy per-child
fallback above the enumeration budget.

The output contract matches the reference (`task.best_resources` gets filled,
optimizer.py:110): each task's `best_resources` becomes a *launchable*
Resources (cloud + concrete type + candidate zone ordering for failover).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


@dataclasses.dataclass
class OptimizedPlan:
    """Per-task choice plus the ordered failover candidates."""
    task: task_lib.Task
    chosen: 'object'            # TpuOffering | InstanceOffering
    candidates: List[object]    # same, price-ascending: the failover order
    hourly_cost: float


def _default_cloud() -> str:
    """'gcp' unless only the fake cloud is enabled (test environments)."""
    from skypilot_tpu import check as check_lib
    enabled = check_lib.get_cached_enabled_clouds()
    if enabled == ['fake']:
        return 'fake'
    return 'gcp'


def optimize_task(task: task_lib.Task,
                  minimize: OptimizeTarget = OptimizeTarget.COST
                  ) -> OptimizedPlan:
    """Fill `task.best_resources`; return the plan with failover ordering."""
    res = task.resources
    # HBM-feasibility gate: a task that declares its training footprint
    # gets its accelerator choice checked against per-chip HBM BEFORE
    # anything is provisioned — the reference lets this OOM at runtime.
    if task.train_footprint is not None and res.tpu is not None:
        from skypilot_tpu import feasibility
        feasibility.check_hbm(task.train_footprint, res.tpu)
    offerings = res.get_offerings()
    if not offerings:
        raise exceptions.ResourcesUnavailableError(
            f'No catalog offering matches {res}. '
            f'Try `skyt show-tpus` for valid TPU types.')
    # COST: price-ascending. TIME: same ordering for now — acquisition-time
    # modeling (stockout history per zone) is a provisioner-level concern and
    # feeds back via the failover blocklist.
    offerings = sorted(offerings,
                       key=lambda o: o.price(res.use_spot))
    chosen = offerings[0]
    cloud = res.cloud or _default_cloud()
    # Record the chosen placement so the provisioner sees the optimizer's
    # choice; keep the user's zone pin (None lets failover roam zones within
    # the chosen region first, then other candidate regions).
    region = res.region if res.region is not None else chosen.region
    if hasattr(chosen, 'topology'):
        best = res.copy(cloud=cloud, tpu=chosen.topology, region=region,
                        zone=res.zone)
    else:
        best = res.copy(cloud=cloud, instance_type=chosen.instance_type,
                        region=region)
    task.best_resources = best
    per_node = chosen.price(res.use_spot)
    return OptimizedPlan(task=task, chosen=chosen, candidates=offerings,
                         hourly_cost=per_node * task.num_nodes)


# GCP inter-region data transfer (GCS cross-region reads / inter-region
# egress, $/GB, conservative list rate). The egress MODEL matches the
# reference's (sky/optimizer.py:77-108 prices parent->child data
# movement); the rate table is GCP-only by design (SURVEY §7 descope).
EGRESS_USD_PER_GB = 0.01
# Without a runtime estimate the egress/hourly trade uses this horizon
# (the reference uses a 1-hour default time estimate the same way).
DEFAULT_RUNTIME_HOURS = 1.0


def _repin(plan: OptimizedPlan, best: 'object') -> None:
    """Move a plan onto offering `best` (a different region): reorder
    failover candidates co-located-first, rebuild best_resources FROM
    the offering (region alone is not enough — the cheapest same-region
    candidate may be a different shape), and pin the region into
    task.resources (the durable spec): managed jobs re-optimize each
    task independently on the controller (execution.launch), and only
    the spec-level pin survives the dag YAML round trip."""
    same_region = [o for o in plan.candidates
                   if o.region == best.region]
    plan.chosen = best
    plan.candidates = same_region + [
        o for o in plan.candidates if o not in same_region]
    res = plan.task.best_resources
    if hasattr(best, 'topology'):
        plan.task.best_resources = res.copy(
            tpu=best.topology, region=best.region)
    else:
        plan.task.best_resources = res.copy(
            instance_type=best.instance_type, region=best.region)
    plan.task.resources = plan.task.resources.copy(region=best.region)
    plan.hourly_cost = (best.price(plan.task.resources.use_spot)
                        * plan.task.num_nodes)


def _cheapest_per_region(plan: OptimizedPlan) -> dict:
    """region -> cheapest offering (candidates are price-ascending)."""
    regs: dict = {}
    for o in plan.candidates:
        regs.setdefault(o.region, o)
    return regs


# Enumeration budget for the joint solve: above this many region
# assignments over the data-connected tasks, fall back to the greedy
# per-child pass (the reference solves the general case with pulp/CBC,
# sky/optimizer.py:472-607; CBC is not in this image, and exhaustive
# search is exact at the DAG sizes tasks actually have).
_JOINT_MAX_ASSIGNMENTS = 200_000


def _joint_egress_placement(dag: dag_lib.Dag,
                            plans: List[OptimizedPlan]) -> bool:
    """JOINT placement over every task touching a data-bearing edge:
    enumerate all region assignments and take the minimum of
    run-cost + egress. Unlike the greedy child pass, this can move a
    PARENT toward its siblings/children — the diamond a->{b,c}->d
    where greedy pins b and c apart (each independently cheapest) and
    then d pays one parent's egress no matter what; the joint optimum
    co-locates all three when the price spread is below the egress.
    Returns False when the assignment space exceeds the enumeration
    budget (caller falls back to greedy)."""
    import itertools

    plan_by_task = {id(p.task): p for p in plans}
    data_edges = [(p, c) for p, c in dag.edges()
                  if p.estimated_output_gb]
    if not data_edges:
        return True                      # nothing to co-locate
    nodes: dict = {}
    for p, c in data_edges:
        nodes[id(p)] = p
        nodes[id(c)] = c
    choices: dict = {}
    total = 1
    for tid, t in nodes.items():
        plan = plan_by_task[tid]
        if t.resources.region is not None:
            # User pin always wins; candidates were already filtered
            # to the pinned region by get_offerings.
            regs = {t.resources.region: plan.chosen}
        else:
            regs = _cheapest_per_region(plan)
        # Price-ascending per task, so enumeration meets each task's
        # cheapest regions first and ties resolve to cheapest-first.
        choices[tid] = list(regs.items())
        total *= len(regs)
        if total > _JOINT_MAX_ASSIGNMENTS:
            return False
    ids = list(nodes)
    run_hours = DEFAULT_RUNTIME_HOURS
    best_cost = float('inf')
    best_assign = None
    for combo in itertools.product(*(choices[tid] for tid in ids)):
        assign = dict(zip(ids, combo))   # tid -> (region, offering)
        cost = sum(
            off.price(nodes[tid].resources.use_spot)
            * nodes[tid].num_nodes * run_hours
            for tid, (_reg, off) in assign.items())
        for p, c in data_edges:
            if assign[id(p)][0] != assign[id(c)][0]:
                cost += p.estimated_output_gb * EGRESS_USD_PER_GB
        if cost < best_cost - 1e-12:
            best_cost = cost
            best_assign = assign
    moved = []
    for tid, (region, off) in best_assign.items():
        t = nodes[tid]
        plan = plan_by_task[tid]
        if t.resources.region is not None:
            continue
        if region != plan.task.best_resources.region:
            _repin(plan, off)
            moved.append(f'{t.name}->{region}')
    if moved:
        logger.info(
            'joint egress placement over %d task(s) / %d data edge(s): '
            'moved %s (total planned cost $%.2f incl. egress)',
            len(nodes), len(data_edges), ', '.join(moved), best_cost)
    return True


def _apply_egress_placement(dag: dag_lib.Dag,
                            plans: List[OptimizedPlan]) -> None:
    """Greedy egress-aware placement (fallback above the joint solve's
    enumeration budget): for each child task with data-bearing parents,
    re-pin the child to the region minimizing run-cost + egress from
    every such parent. Children in topological order so a parent's
    placement is final before its children look at it — per-edge greedy
    would let a second parent re-move a child and silently re-incur the
    first parent's egress. Parents never move toward children here;
    that cross-pull is exactly what the joint solve adds."""
    plan_by_task = {id(p.task): p for p in plans}
    by_child: dict = {}
    for parent, child in dag.edges():
        if parent.estimated_output_gb:
            by_child.setdefault(id(child), []).append(parent)
    for child in dag.topological_order():
        parents = by_child.get(id(child))
        if not parents:
            continue
        c_plan = plan_by_task[id(child)]
        if c_plan.task.resources.region is not None:
            continue   # user pinned the region — always wins
        use_spot = c_plan.task.resources.use_spot
        n = c_plan.task.num_nodes

        def egress_to(region):
            return sum(p.estimated_output_gb * EGRESS_USD_PER_GB
                       for p in parents
                       if plan_by_task[id(p)].task.best_resources.region
                       != region)

        best = min(
            _cheapest_per_region(c_plan).values(),
            key=lambda o: (o.price(use_spot) * n * DEFAULT_RUNTIME_HOURS
                           + egress_to(o.region)))
        if best.region == c_plan.task.best_resources.region:
            continue
        _repin(c_plan, best)
        logger.info(
            'egress-aware placement: %r pinned to region %s (%d '
            'data-bearing parent(s); total remaining egress $%.2f)',
            child.name, best.region, len(parents),
            egress_to(best.region))


def _warn_unpriced_edges(dag: dag_lib.Dag,
                         plans: List[OptimizedPlan]) -> None:
    """A DAG edge that ends up crossing regions with NO declared output
    size moves data the optimizer priced at $0 — say so, naming the
    edge, instead of silently treating the movement as free."""
    plan_by_task = {id(p.task): p for p in plans}
    for parent, child in dag.edges():
        if parent.estimated_output_gb is not None:
            continue
        p_reg = plan_by_task[id(parent)].task.best_resources.region
        c_reg = plan_by_task[id(child)].task.best_resources.region
        if p_reg != c_reg:
            logger.warning(
                'DAG edge %r -> %r crosses regions (%s -> %s) with no '
                'outputs.estimated_size_gb declared on %r: its data '
                'movement is priced at $0. Declare '
                'outputs: {estimated_size_gb: N} to let the optimizer '
                'weigh the egress.',
                parent.name, child.name, p_reg, c_reg, parent.name)


def optimize(dag: dag_lib.Dag,
             minimize: OptimizeTarget = OptimizeTarget.COST,
             quiet: bool = False) -> List[OptimizedPlan]:
    """Optimize every task (chain or general DAG; reference:
    Optimizer.optimize sky/optimizer.py:110, chain DP :411 / ILP :472).
    Per-task minimization is exact for independent tasks; dependency
    edges then get the egress-aware co-location pass — the capability
    the reference's ILP buys, expressed as a post-pass because our
    cost model has no other inter-task coupling (data moves via
    GCS)."""
    dag.resolve_edges()
    plans = [optimize_task(t, minimize) for t in dag.topological_order()]
    if not _joint_egress_placement(dag, plans):
        _apply_egress_placement(dag, plans)
    _warn_unpriced_edges(dag, plans)
    if not quiet:
        print(format_plan_table(plans))
    return plans


def format_plan_table(plans: List[OptimizedPlan]) -> str:
    """Pretty plan table (reference prints via rich, optimizer.py:720)."""
    header = ['TASK', 'RESOURCES', 'ZONE', '$/HR', 'CANDIDATE ZONES']
    if not plans:
        return '(no tasks)'
    rows = []
    for p in plans:
        res = p.task.best_resources
        zones = ', '.join(
            dict.fromkeys(c.zone for c in p.candidates[:4]))
        if len(p.candidates) > 4:
            zones += f', +{len(p.candidates) - 4} more'
        rows.append([
            p.task.name or '-',
            str(res.tpu) if res.tpu else (res.instance_type or 'cpu'),
            p.candidates[0].zone,
            f'{p.hourly_cost:.2f}',
            zones,
        ])
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              for i in range(len(header))]
    lines = ['  '.join(h.ljust(w) for h, w in zip(header, widths))]
    for r in rows:
        lines.append('  '.join(c.ljust(w) for c, w in zip(r, widths)))
    return '\n'.join(lines)
