"""Optimizer: pick the cheapest (or fastest-to-acquire) feasible offering
per task.

Reference equivalent: sky/optimizer.py (1345 LoC: DP over chains at :411, ILP
via pulp for general DAGs at :472). Our Dag is a chain by construction and
tasks have no inter-task egress in the TPU-first design (data moves via GCS),
so per-task independent minimization IS the chain DP — no ILP needed.

The output contract matches the reference (`task.best_resources` gets filled,
optimizer.py:110): each task's `best_resources` becomes a *launchable*
Resources (cloud + concrete type + candidate zone ordering for failover).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


@dataclasses.dataclass
class OptimizedPlan:
    """Per-task choice plus the ordered failover candidates."""
    task: task_lib.Task
    chosen: 'object'            # TpuOffering | InstanceOffering
    candidates: List[object]    # same, price-ascending: the failover order
    hourly_cost: float


def _default_cloud() -> str:
    """'gcp' unless only the fake cloud is enabled (test environments)."""
    from skypilot_tpu import check as check_lib
    enabled = check_lib.get_cached_enabled_clouds()
    if enabled == ['fake']:
        return 'fake'
    return 'gcp'


def optimize_task(task: task_lib.Task,
                  minimize: OptimizeTarget = OptimizeTarget.COST
                  ) -> OptimizedPlan:
    """Fill `task.best_resources`; return the plan with failover ordering."""
    res = task.resources
    # HBM-feasibility gate: a task that declares its training footprint
    # gets its accelerator choice checked against per-chip HBM BEFORE
    # anything is provisioned — the reference lets this OOM at runtime.
    if task.train_footprint is not None and res.tpu is not None:
        from skypilot_tpu import feasibility
        feasibility.check_hbm(task.train_footprint, res.tpu)
    offerings = res.get_offerings()
    if not offerings:
        raise exceptions.ResourcesUnavailableError(
            f'No catalog offering matches {res}. '
            f'Try `skyt show-tpus` for valid TPU types.')
    # COST: price-ascending. TIME: same ordering for now — acquisition-time
    # modeling (stockout history per zone) is a provisioner-level concern and
    # feeds back via the failover blocklist.
    offerings = sorted(offerings,
                       key=lambda o: o.price(res.use_spot))
    chosen = offerings[0]
    cloud = res.cloud or _default_cloud()
    # Record the chosen placement so the provisioner sees the optimizer's
    # choice; keep the user's zone pin (None lets failover roam zones within
    # the chosen region first, then other candidate regions).
    region = res.region if res.region is not None else chosen.region
    if hasattr(chosen, 'topology'):
        best = res.copy(cloud=cloud, tpu=chosen.topology, region=region,
                        zone=res.zone)
    else:
        best = res.copy(cloud=cloud, instance_type=chosen.instance_type,
                        region=region)
    task.best_resources = best
    per_node = chosen.price(res.use_spot)
    return OptimizedPlan(task=task, chosen=chosen, candidates=offerings,
                         hourly_cost=per_node * task.num_nodes)


# GCP inter-region data transfer (GCS cross-region reads / inter-region
# egress, $/GB, conservative list rate). The egress MODEL matches the
# reference's (sky/optimizer.py:77-108 prices parent->child data
# movement); the rate table is GCP-only by design (SURVEY §7 descope).
EGRESS_USD_PER_GB = 0.01
# Without a runtime estimate the egress/hourly trade uses this horizon
# (the reference uses a 1-hour default time estimate the same way).
DEFAULT_RUNTIME_HOURS = 1.0


def _apply_egress_placement(dag: dag_lib.Dag,
                            plans: List[OptimizedPlan]) -> None:
    """Egress-aware placement for DAG edges: when a child task's chosen
    region differs from its parent's and the parent declares
    `outputs: {estimated_size_gb: N}`, re-pin the child to the parent's
    region if hourly-price-delta x runtime < one-off egress cost.
    For each child the decision is made ONCE over all its parents
    (candidate regions scored by run-cost PLUS total egress from every
    data-bearing parent), children in topological order so a parent's
    placement is final before its children look at it — per-edge greedy
    would let a second parent re-move a child and silently re-incur the
    first parent's egress. The winning region is ALSO pinned into
    task.resources (the durable spec): managed jobs re-optimize each
    task independently on the controller (execution.launch), and only
    the spec-level pin survives the dag YAML round trip."""
    plan_by_task = {id(p.task): p for p in plans}
    by_child: dict = {}
    for parent, child in dag.edges():
        if parent.estimated_output_gb:
            by_child.setdefault(id(child), []).append(parent)
    for child in dag.topological_order():
        parents = by_child.get(id(child))
        if not parents:
            continue
        c_plan = plan_by_task[id(child)]
        if c_plan.task.resources.region is not None:
            continue   # user pinned the region — always wins
        use_spot = c_plan.task.resources.use_spot
        n = c_plan.task.num_nodes

        def egress_to(region):
            return sum(p.estimated_output_gb * EGRESS_USD_PER_GB
                       for p in parents
                       if plan_by_task[id(p)].task.best_resources.region
                       != region)

        cheapest_in = {}
        for o in c_plan.candidates:          # price-ascending
            cheapest_in.setdefault(o.region, o)
        best = min(
            cheapest_in.values(),
            key=lambda o: (o.price(use_spot) * n * DEFAULT_RUNTIME_HOURS
                           + egress_to(o.region)))
        if best.region == c_plan.task.best_resources.region:
            continue
        same_region = [o for o in c_plan.candidates
                       if o.region == best.region]
        c_plan.chosen = best
        # Failover still roams: co-located candidates first.
        c_plan.candidates = same_region + [
            o for o in c_plan.candidates if o not in same_region]
        # Rebuild best_resources FROM the new offering (mirror of
        # optimize_task): region alone is not enough — the cheapest
        # same-region candidate may be a different shape.
        c_res = c_plan.task.best_resources
        if hasattr(best, 'topology'):
            c_plan.task.best_resources = c_res.copy(
                tpu=best.topology, region=best.region)
        else:
            c_plan.task.best_resources = c_res.copy(
                instance_type=best.instance_type, region=best.region)
        # Durable pin (see docstring).
        c_plan.task.resources = c_plan.task.resources.copy(
            region=best.region)
        c_plan.hourly_cost = best.price(use_spot) * n
        logger.info(
            'egress-aware placement: %r pinned to region %s (%d '
            'data-bearing parent(s); total remaining egress $%.2f)',
            child.name, best.region, len(parents),
            egress_to(best.region))


def optimize(dag: dag_lib.Dag,
             minimize: OptimizeTarget = OptimizeTarget.COST,
             quiet: bool = False) -> List[OptimizedPlan]:
    """Optimize every task (chain or general DAG; reference:
    Optimizer.optimize sky/optimizer.py:110, chain DP :411 / ILP :472).
    Per-task minimization is exact for independent tasks; dependency
    edges then get the egress-aware co-location pass — the capability
    the reference's ILP buys, expressed as a post-pass because our
    cost model has no other inter-task coupling (data moves via
    GCS)."""
    dag.resolve_edges()
    plans = [optimize_task(t, minimize) for t in dag.topological_order()]
    _apply_egress_placement(dag, plans)
    if not quiet:
        print(format_plan_table(plans))
    return plans


def format_plan_table(plans: List[OptimizedPlan]) -> str:
    """Pretty plan table (reference prints via rich, optimizer.py:720)."""
    header = ['TASK', 'RESOURCES', 'ZONE', '$/HR', 'CANDIDATE ZONES']
    if not plans:
        return '(no tasks)'
    rows = []
    for p in plans:
        res = p.task.best_resources
        zones = ', '.join(
            dict.fromkeys(c.zone for c in p.candidates[:4]))
        if len(p.candidates) > 4:
            zones += f', +{len(p.candidates) - 4} more'
        rows.append([
            p.task.name or '-',
            str(res.tpu) if res.tpu else (res.instance_type or 'cpu'),
            p.candidates[0].zone,
            f'{p.hourly_cost:.2f}',
            zones,
        ])
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              for i in range(len(header))]
    lines = ['  '.join(h.ljust(w) for h, w in zip(header, widths))]
    for r in rows:
        lines.append('  '.join(c.ljust(w) for c, w in zip(r, widths)))
    return '\n'.join(lines)
