"""Optimizer: pick the cheapest (or fastest-to-acquire) feasible offering
per task.

Reference equivalent: sky/optimizer.py (1345 LoC: DP over chains at :411, ILP
via pulp for general DAGs at :472). Our Dag is a chain by construction and
tasks have no inter-task egress in the TPU-first design (data moves via GCS),
so per-task independent minimization IS the chain DP — no ILP needed.

The output contract matches the reference (`task.best_resources` gets filled,
optimizer.py:110): each task's `best_resources` becomes a *launchable*
Resources (cloud + concrete type + candidate zone ordering for failover).
"""
from __future__ import annotations

import dataclasses
import enum
from typing import List, Optional

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu import task as task_lib

logger = sky_logging.init_logger(__name__)


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'


@dataclasses.dataclass
class OptimizedPlan:
    """Per-task choice plus the ordered failover candidates."""
    task: task_lib.Task
    chosen: 'object'            # TpuOffering | InstanceOffering
    candidates: List[object]    # same, price-ascending: the failover order
    hourly_cost: float


def _default_cloud() -> str:
    """'gcp' unless only the fake cloud is enabled (test environments)."""
    from skypilot_tpu import check as check_lib
    enabled = check_lib.get_cached_enabled_clouds()
    if enabled == ['fake']:
        return 'fake'
    return 'gcp'


def optimize_task(task: task_lib.Task,
                  minimize: OptimizeTarget = OptimizeTarget.COST
                  ) -> OptimizedPlan:
    """Fill `task.best_resources`; return the plan with failover ordering."""
    res = task.resources
    # HBM-feasibility gate: a task that declares its training footprint
    # gets its accelerator choice checked against per-chip HBM BEFORE
    # anything is provisioned — the reference lets this OOM at runtime.
    if task.train_footprint is not None and res.tpu is not None:
        from skypilot_tpu import feasibility
        feasibility.check_hbm(task.train_footprint, res.tpu)
    offerings = res.get_offerings()
    if not offerings:
        raise exceptions.ResourcesUnavailableError(
            f'No catalog offering matches {res}. '
            f'Try `skyt show-tpus` for valid TPU types.')
    # COST: price-ascending. TIME: same ordering for now — acquisition-time
    # modeling (stockout history per zone) is a provisioner-level concern and
    # feeds back via the failover blocklist.
    offerings = sorted(offerings,
                       key=lambda o: o.price(res.use_spot))
    chosen = offerings[0]
    cloud = res.cloud or _default_cloud()
    # Record the chosen placement so the provisioner sees the optimizer's
    # choice; keep the user's zone pin (None lets failover roam zones within
    # the chosen region first, then other candidate regions).
    region = res.region if res.region is not None else chosen.region
    if hasattr(chosen, 'topology'):
        best = res.copy(cloud=cloud, tpu=chosen.topology, region=region,
                        zone=res.zone)
    else:
        best = res.copy(cloud=cloud, instance_type=chosen.instance_type,
                        region=region)
    task.best_resources = best
    per_node = chosen.price(res.use_spot)
    return OptimizedPlan(task=task, chosen=chosen, candidates=offerings,
                         hourly_cost=per_node * task.num_nodes)


def optimize(dag: dag_lib.Dag,
             minimize: OptimizeTarget = OptimizeTarget.COST,
             quiet: bool = False) -> List[OptimizedPlan]:
    """Optimize every task in the chain (reference: Optimizer.optimize,
    sky/optimizer.py:110)."""
    plans = [optimize_task(t, minimize) for t in dag.tasks]
    if not quiet:
        print(format_plan_table(plans))
    return plans


def format_plan_table(plans: List[OptimizedPlan]) -> str:
    """Pretty plan table (reference prints via rich, optimizer.py:720)."""
    header = ['TASK', 'RESOURCES', 'ZONE', '$/HR', 'CANDIDATE ZONES']
    if not plans:
        return '(no tasks)'
    rows = []
    for p in plans:
        res = p.task.best_resources
        zones = ', '.join(
            dict.fromkeys(c.zone for c in p.candidates[:4]))
        if len(p.candidates) > 4:
            zones += f', +{len(p.candidates) - 4} more'
        rows.append([
            p.task.name or '-',
            str(res.tpu) if res.tpu else (res.instance_type or 'cpu'),
            p.candidates[0].zone,
            f'{p.hourly_cost:.2f}',
            zones,
        ])
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              for i in range(len(header))]
    lines = ['  '.join(h.ljust(w) for h, w in zip(header, widths))]
    for r in rows:
        lines.append('  '.join(c.ljust(w) for c, w in zip(r, widths)))
    return '\n'.join(lines)
