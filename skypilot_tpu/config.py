"""Paths + user config (reference: sky/skypilot_config.py, 259 LoC).

All client-side state lives under SKYT_HOME (default ~/.skyt), overridable
via env so tests get hermetic state dirs:
    state.db            client state (clusters, enabled clouds, history)
    config.yaml         user config (nested keys via get_nested)
    generated/          rendered cluster configs
    logs/               per-launch client logs
"""
from __future__ import annotations

import functools
import os
import pathlib
import threading
from typing import Any, List, Optional

import yaml

_lock = threading.Lock()
_config_cache: Optional[dict] = None
_config_cache_path: Optional[str] = None


def home_dir() -> pathlib.Path:
    d = pathlib.Path(os.environ.get('SKYT_HOME', '~/.skyt')).expanduser()
    d.mkdir(parents=True, exist_ok=True)
    return d


def state_db_path() -> str:
    return str(home_dir() / 'state.db')


def generated_dir() -> pathlib.Path:
    d = home_dir() / 'generated'
    d.mkdir(parents=True, exist_ok=True)
    return d


def logs_dir() -> pathlib.Path:
    d = home_dir() / 'logs'
    d.mkdir(parents=True, exist_ok=True)
    return d


def _load_config() -> dict:
    global _config_cache, _config_cache_path
    path = str(home_dir() / 'config.yaml')
    with _lock:
        if _config_cache is not None and _config_cache_path == path:
            return _config_cache
        cfg = {}
        if os.path.exists(path):
            with open(path) as f:
                cfg = yaml.safe_load(f) or {}
        _config_cache = cfg
        _config_cache_path = path
        return cfg


def reload() -> None:
    global _config_cache
    with _lock:
        _config_cache = None


def set_active_config(cfg: dict) -> None:
    """Replace the in-process config (admin policies may rewrite it; the
    mutated dict governs the rest of this launch — reference swaps
    skypilot_config the same way)."""
    global _config_cache, _config_cache_path
    with _lock:
        _config_cache = dict(cfg)
        _config_cache_path = str(home_dir() / 'config.yaml')


def get_nested(keys: List[str], default: Any = None) -> Any:
    """config.yaml nested lookup, e.g. get_nested(['gcp', 'project_id'])."""
    node: Any = _load_config()
    for k in keys:
        if not isinstance(node, dict) or k not in node:
            return default
        node = node[k]
    return node
