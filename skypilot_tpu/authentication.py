"""SSH keypair management (reference: sky/authentication.py:107).

One framework keypair under SKYT_HOME/keys; injected into VMs at provision
time (GCP: metadata ssh-keys) and onto the head for head->worker fan-out.
"""
from __future__ import annotations

import os
import subprocess
from typing import Tuple

from skypilot_tpu import config as config_lib


def get_or_generate_keys() -> Tuple[str, str]:
    """Returns (private_key_path, public_key_path).

    Generation is serialized under a file lock: parallel launches (the
    benchmark fan-out, concurrent jobs) otherwise race keygen — one
    caller can observe the private key written but the .pub not yet."""
    from skypilot_tpu.utils import subprocess_utils
    key_dir = config_lib.home_dir() / 'keys'
    key_dir.mkdir(parents=True, exist_ok=True, mode=0o700)
    private = key_dir / 'skyt-key'
    public = key_dir / 'skyt-key.pub'
    if private.exists() and public.exists():
        return str(private), str(public)
    with subprocess_utils.file_lock(str(key_dir / '.keygen.lock')):
        if not (private.exists() and public.exists()):
            # Clear partial state (crashed generation): ssh-keygen
            # refuses to overwrite an existing private key.
            private.unlink(missing_ok=True)
            public.unlink(missing_ok=True)
            try:
                subprocess.run(
                    ['ssh-keygen', '-t', 'ed25519', '-N', '', '-q', '-f',
                     str(private), '-C', 'skypilot-tpu'],
                    check=True)
            except FileNotFoundError:
                _generate_keys_python(private, public)
            os.chmod(private, 0o600)
    return str(private), str(public)


def _generate_keys_python(private, public) -> None:
    """ssh-keygen-free fallback via the `cryptography` package; if that is
    also absent (fake-cloud-only environments never open an SSH
    connection), write placeholder files so paths exist."""
    try:
        from cryptography.hazmat.primitives import serialization
        from cryptography.hazmat.primitives.asymmetric import ed25519
        key = ed25519.Ed25519PrivateKey.generate()
        private.write_bytes(key.private_bytes(
            serialization.Encoding.PEM,
            serialization.PrivateFormat.OpenSSH,
            serialization.NoEncryption()))
        pub = key.public_key().public_bytes(
            serialization.Encoding.OpenSSH,
            serialization.PublicFormat.OpenSSH)
        public.write_bytes(pub + b' skypilot-tpu\n')
    except ImportError:
        private.write_text('# no ssh-keygen/cryptography available\n')
        public.write_text('# no ssh-keygen/cryptography available\n')
