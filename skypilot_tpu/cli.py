"""`skyt` CLI (reference: sky/cli.py, 5551 LoC of click commands).

Verbs mirror the reference so SkyPilot users can switch without relearning:
launch, exec, status, queue, logs, cancel, stop, start, down, autostop,
check, show-tpus, cost-report, jobs {launch,queue,cancel,logs}, serve
{up,status,down}, storage {ls,delete}, bench.
"""
from __future__ import annotations

import os
import sys
from typing import Dict, List, Optional, Tuple

import click

from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)


def _parse_env(env: Tuple[str, ...]) -> Dict[str, str]:
    out = {}
    for item in env:
        if '=' not in item:
            raise click.UsageError(f'--env expects K=V, got {item!r}')
        k, v = item.split('=', 1)
        out[k] = v
    return out


def _load_task(entrypoint: str, env: Tuple[str, ...],
               overrides: Dict[str, object]):
    """Build a Task from a YAML path or inline command, applying CLI
    resource overrides (reference: _make_task_or_dag_from_entrypoint...,
    cli.py:722)."""
    from skypilot_tpu import Resources, Task
    env_overrides = _parse_env(env)
    if os.path.isfile(entrypoint):
        task = Task.from_yaml(entrypoint, env_overrides or None)
    else:
        task = Task(run=entrypoint, envs=env_overrides)
    res_overrides = {k: v for k, v in overrides.items() if v is not None}
    if res_overrides:
        cfg = task.resources.to_yaml_config()
        cfg.update(res_overrides)
        task.resources = Resources.from_yaml_config(cfg)
    return task


def _fmt_age(ts: Optional[float]) -> str:
    import time
    if not ts:
        return '-'
    mins = (time.time() - ts) / 60
    if mins < 60:
        return f'{int(mins)}m'
    if mins < 60 * 24:
        return f'{mins / 60:.0f}h'
    return f'{mins / 1440:.0f}d'


def _table(header: List[str], rows: List[List[str]]) -> str:
    if not rows:
        return '  '.join(header)
    widths = [max(len(header[i]), *(len(r[i]) for r in rows))
              for i in range(len(header))]
    lines = ['  '.join(h.ljust(w) for h, w in zip(header, widths))]
    for r in rows:
        lines.append('  '.join(c.ljust(w) for c, w in zip(r, widths)))
    return '\n'.join(lines)


_RESOURCE_OPTS = [
    click.option('--gpus', '--accelerators', 'accelerators', default=None,
                 help='TPU type, e.g. tpu-v5e-8 (name kept for reference '
                 'compat).'),
    click.option('--cloud', default=None),
    click.option('--region', default=None),
    click.option('--zone', default=None),
    click.option('--use-spot/--no-use-spot', default=None),
    click.option('--cpus', default=None),
    click.option('--num-nodes', type=int, default=None),
]


def _apply_resource_opts(fn):
    for opt in reversed(_RESOURCE_OPTS):
        fn = opt(fn)
    return fn


@click.group()
def cli():
    """skypilot_tpu: run AI workloads on TPU pods."""


# ------------------------------------------------------------------ #
# Cluster verbs
# ------------------------------------------------------------------ #

@cli.command()
@click.argument('entrypoint')
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@click.option('--env', multiple=True, help='K=V env overrides.')
@click.option('--detach-run', '-d', is_flag=True)
@click.option('--dryrun', is_flag=True)
@click.option('--down', is_flag=True,
              help='Tear down the cluster when the job finishes.')
@click.option('--yes', '-y', is_flag=True)
@click.option('--retry-until-up', is_flag=True,
              help='Keep retrying the failover sweep (with backoff) '
                   'until capacity is found.')
@_apply_resource_opts
def launch(entrypoint, cluster, env, detach_run, dryrun, down, yes,
           retry_until_up, accelerators, cloud, region, zone, use_spot,
           cpus, num_nodes):
    """Provision (or reuse) a cluster and run ENTRYPOINT (YAML or cmd)."""
    import skypilot_tpu as sky
    from skypilot_tpu import dag as dag_lib, optimizer
    task = _load_task(entrypoint, env, {
        'accelerators': accelerators, 'cloud': cloud, 'region': region,
        'zone': zone, 'use_spot': use_spot, 'cpus': cpus})
    if num_nodes is not None:
        task.num_nodes = num_nodes
    plan = optimizer.optimize(dag_lib.to_dag(task), quiet=True)[0]
    print(optimizer.format_plan_table([plan]))
    if not yes and not dryrun and sys.stdin.isatty():
        click.confirm('Launch?', abort=True, default=True)
    job_id, handle = sky.launch(task, cluster_name=cluster, dryrun=dryrun,
                                detach_run=detach_run, down=down,
                                quiet_optimizer=True,
                                retry_until_up=retry_until_up)
    if handle is not None and job_id is not None:
        print(f'Job {job_id} on cluster {handle.cluster_name!r}. '
              f'Logs: skyt logs {handle.cluster_name} {job_id}')


@cli.command(name='exec')
@click.argument('cluster')
@click.argument('entrypoint')
@click.option('--env', multiple=True)
@click.option('--detach-run', '-d', is_flag=True)
def exec_cmd(cluster, entrypoint, env, detach_run):
    """Run ENTRYPOINT on an existing cluster (no provisioning)."""
    import skypilot_tpu as sky
    task = _load_task(entrypoint, env, {})
    job_id, _ = sky.exec(task, cluster_name=cluster, detach_run=detach_run)
    if detach_run and job_id is not None:
        print(f'Job {job_id} submitted. Logs: skyt logs {cluster} {job_id}')


@cli.command()
@click.option('--refresh', '-r', is_flag=True,
              help='Reconcile with the cloud before printing.')
def status(refresh):
    """Cluster table (reference: `sky status [-r]`)."""
    from skypilot_tpu import core
    records = core.status(refresh=refresh)
    rows = []
    for r in records:
        handle = r['handle']
        res = str(handle.launched_resources) if handle else '-'
        autostop = (f"{r['autostop']}m{'(down)' if r['to_down'] else ''}"
                    if r['autostop'] >= 0 else '-')
        rows.append([r['name'], _fmt_age(r['launched_at']),
                     r['status'].value, res, autostop])
    print(_table(['NAME', 'AGE', 'STATUS', 'RESOURCES', 'AUTOSTOP'], rows))


@cli.command()
@click.argument('cluster')
def queue(cluster):
    """Job queue of a cluster."""
    from skypilot_tpu import core
    jobs = core.queue(cluster)
    rows = [[str(j['job_id']), j['name'], j['status'],
             _fmt_age(j['submitted_at'])] for j in jobs]
    print(_table(['ID', 'NAME', 'STATUS', 'SUBMITTED'], rows))


@cli.command()
@click.argument('cluster')
@click.argument('job_id', type=int)
@click.option('--follow/--no-follow', default=True)
@click.option('--sync-down', is_flag=True, help='Download instead of tail.')
def logs(cluster, job_id, follow, sync_down):
    """Tail (or download) a job's logs."""
    from skypilot_tpu import core
    if sync_down:
        path = core.download_logs(cluster, job_id, f'./skyt_logs_{job_id}')
        print(f'Logs downloaded to {path}')
        return
    sys.exit(core.tail_logs(cluster, job_id, follow=follow))


@cli.command()
@click.argument('cluster')
@click.argument('job_id', type=int, required=False)
@click.option('--all', 'all_jobs', is_flag=True)
def cancel(cluster, job_id, all_jobs):
    """Cancel a job (or --all)."""
    from skypilot_tpu import core
    if job_id is None and not all_jobs:
        raise click.UsageError('Provide JOB_ID or --all.')
    cancelled = core.cancel(cluster, None if all_jobs else job_id)
    print(f'Cancelled: {cancelled or "nothing"}')


@cli.command()
@click.argument('cluster')
def stop(cluster):
    """Stop a (single-host) cluster; disks persist."""
    from skypilot_tpu import core
    core.stop(cluster)
    print(f'Cluster {cluster!r} stopped.')


@cli.command()
@click.argument('cluster')
def start(cluster):
    """Restart a stopped cluster."""
    from skypilot_tpu import core
    core.start(cluster)
    print(f'Cluster {cluster!r} is UP.')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True)
def down(clusters, yes):
    """Terminate clusters."""
    from skypilot_tpu import core
    if not yes and sys.stdin.isatty():
        click.confirm(f'Tear down {", ".join(clusters)}?', abort=True)
    for name in clusters:
        core.down(name)
        print(f'Cluster {name!r} terminated.')


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes', '-i', type=int, required=True)
@click.option('--down', 'to_down', is_flag=True,
              help='Tear down instead of stopping (required for pods).')
@click.option('--cancel', 'cancel_flag', is_flag=True,
              help='Disable autostop.')
def autostop(cluster, idle_minutes, to_down, cancel_flag):
    """Configure idle autostop/autodown."""
    from skypilot_tpu import core
    if cancel_flag:
        idle_minutes = -1
    core.autostop(cluster, idle_minutes, to_down)
    print(f'Autostop for {cluster!r}: '
          f'{"off" if idle_minutes < 0 else f"{idle_minutes}m"}'
          f'{" (down)" if to_down else ""}')


# ------------------------------------------------------------------ #
# Info verbs
# ------------------------------------------------------------------ #

@cli.command()
def check():
    """Probe cloud credentials and cache enabled clouds."""
    from skypilot_tpu import check as check_lib
    enabled = check_lib.check()
    if not enabled:
        print('No clouds enabled. Configure GCP credentials '
              '(gcloud auth application-default login).')
        sys.exit(1)


@cli.command(name='show-tpus')
@click.argument('name_filter', required=False)
def show_tpus(name_filter):
    """TPU catalog: types, chips/hosts, price (reference: show-gpus)."""
    from skypilot_tpu import catalog
    accs = catalog.list_accelerators(name_filter)
    rows = []
    for name in sorted(accs, key=lambda t: (t.rsplit('-', 1)[0],
                                            int(t.rsplit('-', 1)[1]))):
        offs = accs[name]
        o = offs[0]
        zones = ', '.join(dict.fromkeys(x.zone for x in offs[:3]))
        if len(offs) > 3:
            zones += f', +{len(offs) - 3}'
        rows.append([f'tpu-{name}', str(o.topology.num_chips),
                     str(o.topology.num_hosts), f'${o.price_hr:.2f}',
                     f'${o.spot_price_hr:.2f}', zones])
    print(_table(['TPU', 'CHIPS', 'HOSTS', '$/HR', 'SPOT$/HR',
                  'ZONES'], rows))


@cli.command(name='cost-report')
def cost_report():
    """Accumulated cost per cluster from usage history."""
    from skypilot_tpu import core
    rows = [[r['name'], r['resources'][:40], str(r['num_nodes']),
             f"{r['duration_hours']:.2f}h", f"${r['cost']:.2f}"]
            for r in core.cost_report()]
    print(_table(['NAME', 'RESOURCES', 'NODES', 'DURATION', 'COST'], rows))


# ------------------------------------------------------------------ #
# Managed jobs / serve / storage groups (filled by their subsystems)
# ------------------------------------------------------------------ #

@cli.group()
def jobs():
    """Managed jobs with automatic recovery."""


@jobs.command(name='launch')
@click.argument('entrypoint')
@click.option('--name', '-n', default=None)
@click.option('--env', multiple=True)
@click.option('--controller', type=click.Choice(['local', 'vm']),
              default='local',
              help="'vm' launches the controller onto a framework-"
                   'provisioned cluster (survives this machine).')
@click.option('--yes', '-y', is_flag=True)
def jobs_launch(entrypoint, name, env, controller, yes):
    from skypilot_tpu import dag as dag_lib
    from skypilot_tpu.jobs import core as jobs_core
    if os.path.isfile(entrypoint):
        # One parse for single tasks AND `---`-separated train->eval
        # pipelines (tasks run sequentially with per-task recovery,
        # jobs/controller.py).
        dag = dag_lib.from_yaml(entrypoint, _parse_env(env) or None)
        if len(dag.tasks) == 1:
            task = dag.tasks[0]
            if name:
                task.name = name
        else:
            task = dag
    else:
        task = _load_task(entrypoint, env, {})
        if name:
            task.name = name
    jobs_core.launch(task, name=name, controller=controller)


@jobs.command(name='queue')
def jobs_queue():
    from skypilot_tpu.jobs import core as jobs_core
    rows = [[str(j['job_id']), j['name'], j['status'],
             str(j.get('recoveries', 0)), _fmt_age(j.get('submitted_at')),
             j.get('controller', 'local')]
            for j in jobs_core.queue_all()]
    print(_table(['ID', 'NAME', 'STATUS', 'RECOVERIES', 'SUBMITTED',
                  'CONTROLLER'], rows))


@jobs.command(name='cancel')
@click.argument('job_id', type=int)
@click.option('--controller', type=click.Choice(['local', 'vm']),
              default='local')
def jobs_cancel(job_id, controller):
    from skypilot_tpu.jobs import core as jobs_core
    if controller == 'vm':
        jobs_core.vm_cancel(job_id)
    else:
        jobs_core.cancel(job_id)
    print(f'Managed job {job_id} cancel requested.')


@jobs.command(name='logs')
@click.argument('job_id', type=int)
@click.option('--follow/--no-follow', default=True)
@click.option('--controller', type=click.Choice(['local', 'vm']),
              default='local')
def jobs_logs(job_id, follow, controller):
    from skypilot_tpu.jobs import core as jobs_core
    if controller == 'vm':
        sys.exit(jobs_core.vm_tail_logs(job_id, follow=follow))
    sys.exit(jobs_core.tail_logs(job_id, follow=follow))


@jobs.command(name='dashboard')
@click.option('--port', '-p', type=int, default=8123)
def jobs_dashboard(port):
    from skypilot_tpu.jobs import dashboard
    dashboard.serve(port=port)


@cli.group()
def serve():
    """Serving with replica autoscaling."""


@serve.command(name='up')
@click.argument('entrypoint')
@click.option('--service-name', '-n', default=None)
@click.option('--controller', type=click.Choice(['local', 'vm']),
              default='local',
              help="'vm' runs the controller + load balancer on a "
                   'framework-provisioned cluster.')
@click.option('--yes', '-y', is_flag=True)
def serve_up(entrypoint, service_name, controller, yes):
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu import Task
    task = Task.from_yaml(entrypoint)
    serve_core.up(task, service_name=service_name, controller=controller)


@serve.command(name='update')
@click.argument('service_name')
@click.argument('entrypoint')
@click.option('--controller', type=click.Choice(['local', 'vm']),
              default='local')
@click.option('--yes', '-y', is_flag=True)
def serve_update(service_name, entrypoint, controller, yes):
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu import Task
    task = Task.from_yaml(entrypoint)
    if not yes:
        click.confirm(f'Update service {service_name!r}?', abort=True,
                      default=True)
    if controller == 'vm':
        version = serve_core.vm_update(service_name, task)
    else:
        version = serve_core.update(service_name, task)
    print(f'Service {service_name!r} rolling to version {version}.')


@serve.command(name='status')
@click.argument('service_name', required=False)
def serve_status(service_name):
    from skypilot_tpu.serve import core as serve_core
    for svc in serve_core.status_all(service_name):
        print(svc)


@serve.command(name='down')
@click.argument('service_name')
@click.option('--controller', type=click.Choice(['local', 'vm']),
              default='local')
@click.option('--yes', '-y', is_flag=True)
def serve_down(service_name, controller, yes):
    from skypilot_tpu.serve import core as serve_core
    if controller == 'vm':
        serve_core.vm_down(service_name)
    else:
        serve_core.down(service_name)
    print(f'Service {service_name!r} torn down.')


@serve.command(name='logs')
@click.argument('service_name')
@click.option('--replica', '-r', type=int, default=None,
              help='Tail this replica\'s job log instead of the '
                   'controller log.')
@click.option('--no-follow', is_flag=True)
@click.option('--controller', type=click.Choice(['local', 'vm']),
              default='local')
def serve_logs(service_name, replica, no_follow, controller):
    from skypilot_tpu.serve import core as serve_core
    if controller == 'vm':
        sys.exit(serve_core.vm_tail_logs(service_name, replica_id=replica,
                                         follow=not no_follow))
    sys.exit(serve_core.tail_logs(service_name, replica_id=replica,
                                  follow=not no_follow))


@serve.command(name='dashboard')
@click.option('--port', '-p', type=int, default=8124)
def serve_dashboard(port):
    from skypilot_tpu.serve import dashboard
    dashboard.serve(port=port)


@cli.group()
def bench():
    """Benchmark a task across candidate TPU types (reference: sky bench)."""


@bench.command(name='launch')
@click.argument('entrypoint')
@click.option('--benchmark', '-b', 'bench_name', required=True,
              help='Benchmark name.')
@click.option('--gpus', '--accelerators', 'accelerators', multiple=True,
              required=True,
              help='Candidate TPU types, e.g. -b x --gpus tpu-v5e-8 '
                   '--gpus tpu-v4-8.')
@click.option('--env', multiple=True)
@click.option('--yes', '-y', is_flag=True)
def bench_launch(entrypoint, bench_name, accelerators, env, yes):
    from skypilot_tpu.benchmark import utils as bench_utils
    task = _load_task(entrypoint, env, {})
    candidates = [{'tpu': acc} for acc in accelerators]
    if not yes:
        click.confirm(
            f'Launch {len(candidates)} benchmark clusters?', abort=True,
            default=True)
    names = bench_utils.launch_benchmark(task, bench_name, candidates)
    print(f'Benchmark {bench_name!r}: launched {len(names)} candidates.')
    print(f'Watch with: skyt bench show {bench_name}')


@bench.command(name='show')
@click.argument('benchmark')
def bench_show(benchmark):
    from skypilot_tpu.benchmark import utils as bench_utils
    bench_utils.update_benchmark(benchmark)
    print(bench_utils.format_report(benchmark))


@bench.command(name='ls')
def bench_ls():
    from skypilot_tpu.benchmark import state as bench_state
    rows = [[b['name'], b['task_name'], _fmt_age(b['launched_at'])]
            for b in bench_state.get_benchmarks()]
    print(_table(['BENCHMARK', 'TASK', 'AGE'], rows))


@bench.command(name='down')
@click.argument('benchmark')
@click.option('--yes', '-y', is_flag=True)
def bench_down(benchmark, yes):
    from skypilot_tpu.benchmark import utils as bench_utils
    if not yes:
        click.confirm(f'Tear down benchmark {benchmark!r} clusters?',
                      abort=True)
    bench_utils.teardown_benchmark(benchmark)
    print(f'Benchmark {benchmark!r} clusters terminated.')


@bench.command(name='delete')
@click.argument('benchmark')
@click.option('--force', is_flag=True,
              help='Delete tracking even if clusters are still up.')
@click.option('--yes', '-y', is_flag=True)
def bench_delete(benchmark, force, yes):
    from skypilot_tpu.benchmark import utils as bench_utils
    if not yes:
        click.confirm(f'Delete benchmark {benchmark!r} records?',
                      abort=True)
    bench_utils.delete_benchmark(benchmark, force=force)
    print(f'Benchmark {benchmark!r} deleted.')


@cli.group()
def storage():
    """Bucket lifecycle."""


@storage.command(name='ls')
def storage_ls():
    from skypilot_tpu import global_user_state
    rows = [[s['name'], s['status'], _fmt_age(s['launched_at'])]
            for s in global_user_state.get_storage()]
    print(_table(['NAME', 'STATUS', 'AGE'], rows))


@storage.command(name='delete')
@click.argument('name')
@click.option('--yes', '-y', is_flag=True)
def storage_delete(name, yes):
    from skypilot_tpu.data import storage as storage_lib
    storage_lib.delete_storage(name)
    print(f'Storage {name!r} deleted.')


def main():
    try:
        cli(standalone_mode=True)
    except Exception as e:  # noqa: BLE001 — user-facing error formatting
        from skypilot_tpu import exceptions
        if isinstance(e, exceptions.SkyTpuError):
            print(f'\x1b[31mError:\x1b[0m {e}', file=sys.stderr)
            sys.exit(1)
        raise


if __name__ == '__main__':
    main()
