"""skypilot_tpu: a TPU-native orchestration + training/serving framework.

Capability surface of SkyPilot (reference at /root/reference), re-designed
TPU-first: Task YAML -> cost optimizer over a TPU catalog -> TPU-VM/pod-slice
provisioner with zone failover -> SSH gang executor with a jax.distributed
rendezvous contract (no Ray) -> managed jobs / serving / storage on top, and
an in-repo JAX compute path (models, pallas ops, SPMD parallelism) for the
workloads the reference delegates to user frameworks.
"""
__version__ = '0.1.0'

from skypilot_tpu.dag import Dag
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task
from skypilot_tpu.tpu_topology import TpuTopology, parse_tpu_type


def __getattr__(name):
    """Lazy entry points so `import skypilot_tpu` stays fast and partial
    builds remain importable."""
    if name == 'optimize':
        from skypilot_tpu import optimizer
        return optimizer.optimize
    if name in ('launch', 'exec'):
        from skypilot_tpu import execution
        return getattr(execution, name)
    if name in ('status', 'start', 'stop', 'down', 'autostop', 'queue',
                'cancel', 'tail_logs', 'cost_report'):
        from skypilot_tpu import core
        return getattr(core, name)
    if name in ('Storage', 'StorageMode', 'StoreType'):
        from skypilot_tpu.data import storage
        return getattr(storage, name)
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')
