"""Cloud-URI storage command builders for file_mounts.

Reference: sky/cloud_stores.py (561 LoC; `CloudStorage.is_directory`,
`make_sync_dir_command`, `make_sync_file_command` per scheme, registry at
the bottom). A task's `file_mounts: {dst: gs://bucket/path}` is satisfied
by running the returned command ON THE CLUSTER HOSTS, so these builders
emit plain shell (gcloud storage / gsutil) rather than calling SDKs —
hosts have cloud CLIs, the client may not.

GCS-first like the rest of the framework; `file://` is the offline test
scheme (fake cloud hosts share the client filesystem)."""
from __future__ import annotations

import shlex
from typing import Dict, Type

from skypilot_tpu import exceptions


def _quote_dest(path: str) -> str:
    """Quote a destination path while keeping '~/...' expandable: the
    command runs on the cluster host where HOME differs from the client
    (and the fake cloud remaps it), so a shlex-quoted literal '~' would
    never resolve."""
    if path == '~' or path.startswith('~/'):
        rest = path[1:].lstrip('/')
        return f'"$HOME/{rest}"'
    return shlex.quote(path)


class CloudStorage:
    """Per-scheme command builders (reference: cloud_stores.py:32)."""

    def is_directory(self, url: str) -> bool:
        """Best-effort: whether url names a 'directory' (prefix)."""
        raise NotImplementedError

    def make_sync_dir_command(self, source: str, destination: str) -> str:
        raise NotImplementedError

    def make_sync_file_command(self, source: str, destination: str) -> str:
        raise NotImplementedError


def gcs_cli_cmd(args: str) -> str:
    """`gcloud storage` with gsutil fallback (the newer CLI is markedly
    faster for many-object rsync). Shared with data/data_transfer.py."""
    return ('(command -v gcloud >/dev/null && '
            f'gcloud storage {args} || gsutil -m {args})')


class GcsCloudStorage(CloudStorage):
    """gs:// command builders running on the cluster host."""

    def is_directory(self, url: str) -> bool:
        # The client may have no GCS credentials, so prefix-vs-object is
        # resolved REMOTELY: report True and let make_sync_dir_command's
        # rsync-else-cp fallback handle single objects.
        del url
        return True

    def make_sync_dir_command(self, source: str, destination: str) -> str:
        dest_q = _quote_dest(destination)
        src_q = shlex.quote(source.rstrip('/'))
        rsync = gcs_cli_cmd(f'rsync -r {src_q} {dest_q}')
        cp = gcs_cli_cmd(f'cp {src_q} {dest_q}')
        # Prefix -> rsync into the pre-made dir. Single object -> rsync
        # fails; drop the (empty) dir so cp lands the file AT the
        # destination path, not nested inside it.
        return (f'mkdir -p {dest_q} && '
                f'({rsync} || (rmdir {dest_q} 2>/dev/null; {cp}))')

    def make_sync_file_command(self, source: str, destination: str) -> str:
        dest_q = _quote_dest(destination)
        src_q = shlex.quote(source)
        inner = gcs_cli_cmd(f'cp {src_q} {dest_q}')
        return f'mkdir -p $(dirname {dest_q}) && {inner}'


class FileCloudStorage(CloudStorage):
    """file:// for the fake cloud: hosts see the client filesystem, so a
    plain cp is the 'cloud fetch'. Keeps the whole file-mount path
    testable offline (the substrate gap SURVEY.md §4 calls out)."""

    def _path(self, url: str) -> str:
        return url[len('file://'):]

    def is_directory(self, url: str) -> bool:
        import os
        return os.path.isdir(self._path(url))

    def make_sync_dir_command(self, source: str, destination: str) -> str:
        src = shlex.quote(self._path(source).rstrip('/'))
        dst = _quote_dest(destination)
        return f'mkdir -p {dst} && cp -r {src}/. {dst}/'

    def make_sync_file_command(self, source: str, destination: str) -> str:
        src = shlex.quote(self._path(source))
        dst = _quote_dest(destination)
        return f'mkdir -p $(dirname {dst}) && cp {src} {dst}'


_REGISTRY: Dict[str, Type[CloudStorage]] = {
    'gs://': GcsCloudStorage,
    'file://': FileCloudStorage,
}


def is_cloud_store_url(url: str) -> bool:
    return any(url.startswith(scheme) for scheme in _REGISTRY)


def get_storage_from_path(url: str) -> CloudStorage:
    for scheme, cls in _REGISTRY.items():
        if url.startswith(scheme):
            return cls()
    raise exceptions.StorageSpecError(
        f'Unsupported storage URL scheme: {url!r} '
        f'(supported: {", ".join(_REGISTRY)})')
