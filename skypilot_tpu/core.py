"""Non-launch verbs: status/start/stop/down/queue/cancel/logs/autostop/
cost-report (reference: sky/core.py, 925 LoC)."""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision
from skypilot_tpu import sky_logging
from skypilot_tpu.backend import CloudTpuBackend, ClusterHandle
from skypilot_tpu.provision import common as provision_common
from skypilot_tpu.provision import provisioner

logger = sky_logging.init_logger(__name__)


def _get_handle(cluster_name: str) -> ClusterHandle:
    record = global_user_state.get_cluster(cluster_name)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    return record['handle']


def _agent_healthy(handle: ClusterHandle) -> bool:
    """Probe the head daemon's liveness heartbeat over the cluster's
    runner (reference: the ray-cluster health check folded into
    _update_cluster_status_no_lock, backend_utils.py:1929). The daemon
    rewrites ~/.skyt_agent/daemon.hb every event-loop tick; a stale or
    missing file with the VMs still RUNNING means the on-cluster runtime
    is dead — the cluster cannot run jobs even though the cloud reports
    it up."""
    import os
    stale_after = float(os.environ.get(
        'SKYT_AGENT_HEARTBEAT_STALE_SECONDS', '90'))
    from skypilot_tpu.agent import constants as agent_constants
    probe = (
        'python3 -c "import os,time; '
        'p=os.path.expanduser('
        f"'{agent_constants.DAEMON_HEARTBEAT}'); "
        "print('HB_AGE:%d' % (time.time()-os.path.getmtime(p)) "
        "if os.path.exists(p) else 'HB_AGE:-1')\"")
    # A SUCCESSFUL probe reporting a stale/missing heartbeat is
    # definitive. A FAILED probe (SSH blip) is retried: a single
    # transient failure must not flip UP->INIT — the managed-jobs
    # controller treats a non-UP cluster as preempted and would tear
    # down and relaunch a healthy cluster (jobs/controller.py
    # _cluster_alive).
    import time as time_lib
    probe_timeout = float(os.environ.get(
        'SKYT_AGENT_PROBE_TIMEOUT_SECONDS', '10'))
    for attempt in range(3):
        try:
            rc, out, _ = handle.head_runner().run(
                probe, require_outputs=True, timeout=probe_timeout)
        except Exception:  # noqa: BLE001 — head unreachable; retry
            rc, out = 1, ''
        if rc == 0:
            for line in out.splitlines():
                if line.startswith('HB_AGE:'):
                    age = float(line[len('HB_AGE:'):])
                    return 0 <= age <= stale_after
            return False
        if attempt < 2:
            time_lib.sleep(1)
    return False


def _refresh_one(record: Dict[str, Any]) -> Dict[str, Any]:
    """Reconcile DB state with the cloud AND the on-cluster runtime
    (reference: _update_cluster_status_no_lock, backend_utils.py:1929 +
    the state machine in design_docs/cluster_status.md):
      * all instances RUNNING + agent heartbeat fresh -> keep/mark UP
      * all RUNNING but agent dead/stale (past an INIT grace period
        after launch) -> INIT (provisioned but not operational)
      * any STOPPED           -> STOPPED (whole cluster must be stopped)
      * none found            -> cluster is gone; drop the record
    """
    import os
    import time as time_lib
    handle: Optional[ClusterHandle] = record['handle']
    if handle is None:
        return record
    name = record['name']
    try:
        statuses = provision.query_instances(handle.cloud, name,
                                             getattr(handle, 'provider_config', {}))
    except Exception as e:  # noqa: BLE001 — cloud probe failed; keep as-is
        logger.debug(f'status refresh failed for {name}: {e}')
        return record
    if not statuses:
        global_user_state.remove_cluster(name)
        record = dict(record)
        record['status'] = None
        return record
    values = set(statuses.values())
    if values == {provision_common.InstanceStatus.RUNNING}:
        new_status = global_user_state.ClusterStatus.UP
        # Health layer: VMs up but runtime dead -> INIT. A grace period
        # after launch keeps a just-provisioned cluster (daemon not yet
        # started / first heartbeat pending) from flapping.
        grace = float(os.environ.get('SKYT_INIT_GRACE_SECONDS', '120'))
        past_grace = (time_lib.time() - (record['launched_at'] or 0)
                      > grace)
        if past_grace and not _agent_healthy(handle):
            logger.warning(f'Cluster {name!r}: instances RUNNING but the '
                           'agent daemon heartbeat is stale/missing; '
                           'marking INIT (restart with `skyt start`).')
            new_status = global_user_state.ClusterStatus.INIT
    elif provision_common.InstanceStatus.STOPPED in values:
        new_status = global_user_state.ClusterStatus.STOPPED
    else:
        new_status = global_user_state.ClusterStatus.INIT
    if new_status != record['status']:
        global_user_state.set_cluster_status(name, new_status)
        record = dict(record)
        record['status'] = new_status
    return record


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = False) -> List[Dict[str, Any]]:
    """Cluster table (reference: core.status / `sky status -r`)."""
    records = global_user_state.get_clusters()
    if cluster_names is not None:
        records = [r for r in records if r['name'] in cluster_names]
    if refresh:
        # Parallel: each refresh may probe the head over SSH (worst
        # case ~30s for an unreachable host); serial would make `skyt
        # status -r` scale with cluster count x probe time.
        from skypilot_tpu.utils import subprocess_utils
        records = subprocess_utils.run_in_parallel(_refresh_one, records)
        records = [r for r in records if r['status'] is not None]
    return records


def start(cluster_name: str) -> None:
    """Restart a STOPPED cluster (reference: core.start — `sky start`)."""
    handle = _get_handle(cluster_name)
    record = global_user_state.get_cluster(cluster_name)
    if record['status'] == global_user_state.ClusterStatus.UP:
        logger.info(f'Cluster {cluster_name!r} is already UP.')
        return
    res = handle.launched_resources
    offerings = res.get_offerings()
    result = provisioner.provision_with_failover(
        cluster_name=cluster_name, cloud=handle.cloud, resources=res,
        num_nodes=handle.launched_nodes, candidates=offerings)
    handle.cluster_info = result.cluster_info
    handle.provider_config = result.provider_config
    global_user_state.add_or_update_cluster(
        cluster_name, handle, global_user_state.ClusterStatus.INIT,
        is_launch=True)
    provisioner.wait_for_connectivity(result.cluster_info)
    provisioner.setup_runtime_on_cluster(result.cluster_info)
    provisioner.start_agent_daemon(result.cluster_info)
    global_user_state.set_cluster_status(
        cluster_name, global_user_state.ClusterStatus.UP)


def stop(cluster_name: str) -> None:
    CloudTpuBackend().stop(_get_handle(cluster_name))


def down(cluster_name: str) -> None:
    CloudTpuBackend().teardown(_get_handle(cluster_name))


def autostop(cluster_name: str, idle_minutes: int,
             down_after: bool = False) -> None:
    CloudTpuBackend().set_autostop(_get_handle(cluster_name), idle_minutes,
                                   down_after)


def queue(cluster_name: str) -> List[Dict[str, Any]]:
    return CloudTpuBackend().get_job_queue(_get_handle(cluster_name))


def cancel(cluster_name: str,
           job_id: Optional[int] = None) -> List[int]:
    return CloudTpuBackend().cancel_jobs(_get_handle(cluster_name), job_id)


def tail_logs(cluster_name: str, job_id: int, follow: bool = True) -> int:
    return CloudTpuBackend().tail_logs(_get_handle(cluster_name), job_id,
                                       follow)


def download_logs(cluster_name: str, job_id: int, local_dir: str) -> str:
    return CloudTpuBackend().sync_down_logs(_get_handle(cluster_name),
                                            job_id, local_dir)


def job_status(cluster_name: str, job_id: int) -> Optional[str]:
    return CloudTpuBackend().get_job_status(_get_handle(cluster_name),
                                            job_id)


def cost_report() -> List[Dict[str, Any]]:
    return global_user_state.get_cost_report()
