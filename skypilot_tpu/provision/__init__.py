"""Per-cloud provisioner dispatch (reference: sky/provision/__init__.py —
the 11-function protocol at :65-204, dispatched by module name via
`_route_to_cloud_impl`).

Every cloud module under skypilot_tpu/provision/<cloud>/instance.py
implements:
    bootstrap_config(config) -> ProvisionConfig
    run_instances(config) -> ProvisionRecord
    wait_instances(region, cluster_name, state) -> None
    stop_instances(cluster_name, provider_config) -> None
    terminate_instances(cluster_name, provider_config) -> None
    query_instances(cluster_name, provider_config) -> Dict[str, str]
    get_cluster_info(region, cluster_name, provider_config) -> ClusterInfo
    open_ports / cleanup_ports(cluster_name, ports, provider_config)
"""
from __future__ import annotations

import importlib
from typing import Any


def _impl(cloud: str):
    return importlib.import_module(f'skypilot_tpu.provision.{cloud}.instance')


def _route(fn_name: str):
    def wrapper(cloud: str, *args: Any, **kwargs: Any) -> Any:
        module = _impl(cloud)
        fn = getattr(module, fn_name, None)
        if fn is None:
            raise NotImplementedError(
                f'Cloud {cloud!r} does not implement {fn_name}')
        return fn(*args, **kwargs)
    wrapper.__name__ = fn_name
    return wrapper


bootstrap_config = _route('bootstrap_config')
run_instances = _route('run_instances')
wait_instances = _route('wait_instances')
stop_instances = _route('stop_instances')
terminate_instances = _route('terminate_instances')
query_instances = _route('query_instances')
get_cluster_info = _route('get_cluster_info')
open_ports = _route('open_ports')
cleanup_ports = _route('cleanup_ports')
