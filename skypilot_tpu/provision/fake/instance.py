"""Fake cloud: localhost directories impersonating TPU hosts.

This is the in-repo test substrate the reference lacks (SURVEY.md §4: "no
fake provisioner/in-memory cloud" — multi-node behavior there is only
covered by real-cloud smoke tests). Every capability of the real provider
protocol is modeled:

  * a "node" is a TPU slice; a multi-host slice materializes as N host
    directories, each reachable via LocalCommandRunner with HOME remapped —
    so the gang executor, agent, and env contract run exactly as on real
    pods, minus the network.
  * capacity injection: `capacity.json` at the fake-cloud root can declare
    per-zone remaining slices or region-level quota failure, driving the
    failover engine in tests (the reference can only test failover against
    live clouds).

Layout under $SKYT_HOME/fake_cloud/:
    capacity.json                      (optional, written by tests)
    clusters/<name>/meta.json
    clusters/<name>/node<i>-host<j>/   (one dir per host = one "VM")
"""
from __future__ import annotations

import json
import os
import pathlib
import shutil
from typing import Any, Dict, List, Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions
from skypilot_tpu.provision import common

PROVIDER_NAME = 'fake'


def _root() -> pathlib.Path:
    d = config_lib.home_dir() / 'fake_cloud'
    (d / 'clusters').mkdir(parents=True, exist_ok=True)
    return d


def _cluster_dir(cluster_name: str) -> pathlib.Path:
    return _root() / 'clusters' / cluster_name


def _meta_path(cluster_name: str) -> pathlib.Path:
    return _cluster_dir(cluster_name) / 'meta.json'


def _load_meta(cluster_name: str) -> Optional[Dict[str, Any]]:
    p = _meta_path(cluster_name)
    if not p.exists():
        return None
    return json.loads(p.read_text())


def _save_meta(cluster_name: str, meta: Dict[str, Any]) -> None:
    _meta_path(cluster_name).write_text(json.dumps(meta, indent=2))


# ------------------------------------------------------------------ #
# Capacity injection for failover tests
# ------------------------------------------------------------------ #

def _capacity() -> Dict[str, Any]:
    p = _root() / 'capacity.json'
    if p.exists():
        return json.loads(p.read_text())
    return {}


def set_capacity(zones: Optional[Dict[str, int]] = None,
                 quota_fail_regions: Optional[List[str]] = None) -> None:
    """Test hook: limit per-zone slice capacity / fail regions on quota."""
    (_root() / 'capacity.json').write_text(json.dumps({
        'zones': zones or {},
        'quota_fail_regions': quota_fail_regions or [],
    }))


def _check_and_take_capacity(zone: str, region: str, n: int) -> None:
    cap = _capacity()
    if region in cap.get('quota_fail_regions', []):
        raise exceptions.QuotaExceededError(
            f'[fake] Quota QUOTA_EXCEEDED in region {region}')
    zones = cap.get('zones')
    if zones is None or zone not in (zones or {}):
        return  # unlimited
    remaining = zones[zone]
    if remaining < n:
        raise exceptions.TpuCapacityError(
            f'[fake] There is no more capacity in the zone {zone!r}; '
            f'requested {n}, have {remaining}.')
    zones[zone] = remaining - n
    (_root() / 'capacity.json').write_text(json.dumps(cap))


# ------------------------------------------------------------------ #
# Protocol implementation
# ------------------------------------------------------------------ #

def bootstrap_config(config: common.ProvisionConfig
                     ) -> common.ProvisionConfig:
    """No IAM/VPC to set up; identity function (reference analog:
    gcp/config.py bootstrap_instances)."""
    return config


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    meta = _load_meta(config.cluster_name)
    res = config.resources
    hosts_per_node = res.num_hosts()
    created, resumed = [], []
    if meta is None:
        _check_and_take_capacity(config.zone, config.region,
                                 config.num_nodes)
        meta = {
            'cluster_name': config.cluster_name,
            'region': config.region,
            'zone': config.zone,
            'num_nodes': config.num_nodes,
            'hosts_per_node': hosts_per_node,
            'tpu_type': res.tpu.type_name if res.tpu else None,
            'instance_type': res.instance_type,
            'use_spot': res.use_spot,
            'status': 'RUNNING',
        }
        for node in range(config.num_nodes):
            for host in range(hosts_per_node):
                iid = f'node{node}-host{host}'
                (_cluster_dir(config.cluster_name) / iid).mkdir(
                    parents=True, exist_ok=True)
                created.append(iid)
        _save_meta(config.cluster_name, meta)
    else:
        if meta['status'] == 'STOPPED':
            meta['status'] = 'RUNNING'
            _save_meta(config.cluster_name, meta)
            resumed = [i.instance_id for i in _instances(meta)]
    if config.ports:
        # Same contract as the GCP provider (gcp/instance.py:149): a
        # task with `ports:` gets them opened at provision time.
        open_ports(config.cluster_name, config.ports,
                   config.provider_config)
    return common.ProvisionRecord(
        provider_name=PROVIDER_NAME, cluster_name=config.cluster_name,
        region=config.region, zone=config.zone,
        resumed_instance_ids=resumed, created_instance_ids=created)


def _instances(meta: Dict[str, Any]) -> List[common.InstanceInfo]:
    out = []
    name = meta['cluster_name']
    for node in range(meta['num_nodes']):
        for host in range(meta['hosts_per_node']):
            iid = f'node{node}-host{host}'
            host_dir = str(_cluster_dir(name) / iid)
            # Deterministic fake internal IPs (per-node subnet).
            ip = f'10.{(hash(name) % 200) + 10}.{node}.{host + 2}'
            out.append(common.InstanceInfo(
                instance_id=iid, internal_ip=ip, external_ip='127.0.0.1',
                node_index=node, host_index=host,
                runner_spec={'kind': 'local', 'host_dir': host_dir}))
    return out


def wait_instances(region: str, cluster_name: str,
                   state: Optional[str] = None,
                   provider_config: Optional[Dict] = None) -> None:
    """Directories are instantly 'booted'."""
    del region, cluster_name, state, provider_config


def stop_instances(cluster_name: str,
                   provider_config: Optional[Dict] = None) -> None:
    meta = _load_meta(cluster_name)
    if meta is None:
        return
    if meta['hosts_per_node'] > 1:
        # Mirror real TPU semantics: pods cannot stop (gcp.py:193-197).
        raise exceptions.NotSupportedError(
            'TPU pod slices cannot be stopped; use down.')
    meta['status'] = 'STOPPED'
    _save_meta(cluster_name, meta)


def terminate_instances(cluster_name: str,
                        provider_config: Optional[Dict] = None) -> None:
    import time
    cleanup_ports(cluster_name, [], provider_config)
    d = _cluster_dir(cluster_name)
    # Kill + delete with retries: executors/daemons may still be writing
    # logs while the tree is being removed.
    for attempt in range(5):
        if not d.exists():
            return
        _kill_host_processes(d)
        try:
            shutil.rmtree(d)
            return
        except OSError:
            time.sleep(0.2 * (attempt + 1))
    if d.exists():
        shutil.rmtree(d, ignore_errors=True)


def _kill_host_processes(cluster_dir: pathlib.Path) -> None:
    """Terminating a real TPU kills everything on it; the fake cloud must
    match, or 'preempted' replica/job processes would keep running (and
    keep answering readiness probes). Job pgids are recorded in the
    executor's pidfiles; the daemon records its own."""
    import signal
    import sqlite3
    pids = []
    for pid_file in cluster_dir.rglob('*.pid'):
        try:
            pids.append(int(pid_file.read_text().strip()))
        except (ValueError, OSError):
            continue
    # Gang executors record their pid in the head's jobs.db, not a file.
    for db in cluster_dir.rglob('.skyt_agent/jobs.db'):
        try:
            conn = sqlite3.connect(db)
            # Only live jobs: a finished executor's PID may have been
            # recycled by the OS for an unrelated process.
            rows = conn.execute(
                "SELECT executor_pid FROM jobs WHERE executor_pid IS NOT "
                "NULL AND status IN ('PENDING','SETTING_UP','RUNNING')"
            ).fetchall()
            conn.close()
            pids.extend(r[0] for r in rows)
        except sqlite3.Error:
            continue
    # A recorded pid may be the C++ supervisor (whose own group holds only
    # itself — the job tree lives in the child's group and in
    # setsid-escaped descendants), so a bare killpg(SIGKILL) would kill
    # the supervisor and LEAK the tree. Sweep full descendant sets from
    # /proc instead, then kill groups/pids as backstop.
    own_pgid = os.getpgid(0)
    doomed: set = set()
    ppids = _proc_ppid_map()
    frontier = list(pids)
    while frontier:
        cur = frontier.pop()
        for child_pid, ppid in ppids:
            if ppid == cur and child_pid not in doomed:
                doomed.add(child_pid)
                frontier.append(child_pid)
    for pid in set(pids) | doomed:
        if pid == os.getpid():
            continue
        try:
            pgid = os.getpgid(pid)
            if pgid == pid and pgid != own_pgid:
                os.killpg(pgid, signal.SIGKILL)
            else:
                os.kill(pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


def _proc_ppid_map() -> list:
    """[(pid, ppid)] snapshot from /proc (parse from the last ')' of
    /proc/<pid>/stat — comm may contain spaces)."""
    out = []
    for entry in os.listdir('/proc'):
        if not entry.isdigit():
            continue
        try:
            with open(f'/proc/{entry}/stat') as f:
                stat = f.read()
            after = stat.rsplit(')', 1)[1].split()
            out.append((int(entry), int(after[1])))
        except (OSError, IndexError, ValueError):
            continue
    return out


def query_instances(cluster_name: str,
                    provider_config: Optional[Dict] = None
                    ) -> Dict[str, str]:
    meta = _load_meta(cluster_name)
    if meta is None:
        return {}
    status = (common.InstanceStatus.RUNNING
              if meta['status'] == 'RUNNING'
              else common.InstanceStatus.STOPPED)
    return {i.instance_id: status for i in _instances(meta)}


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Optional[Dict] = None
                     ) -> common.ClusterInfo:
    meta = _load_meta(cluster_name)
    if meta is None:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    return common.ClusterInfo(
        provider_name=PROVIDER_NAME, cluster_name=cluster_name,
        region=meta['region'], zone=meta['zone'],
        instances=_instances(meta), ssh_user=os.environ.get('USER', 'user'))


def _ports_path() -> pathlib.Path:
    return _root() / 'ports.json'


def opened_ports() -> Dict[str, List[int]]:
    """Firewall state observable by tests: cluster -> open port list."""
    p = _ports_path()
    if not p.exists():
        return {}
    return json.loads(p.read_text())


def _ports_lock():
    from skypilot_tpu.utils import subprocess_utils
    return subprocess_utils.file_lock(str(_root() / '.ports.lock'))


def open_ports(cluster_name: str, ports: List[int],
               provider_config: Optional[Dict] = None) -> None:
    """Record the firewall rule (localhost needs no real firewall; tests
    assert the provider was asked to open the right ports — the thing
    that would have been silently skipped on real GCP, VERDICT r2 #4).
    ports.json is shared across clusters, so the read-modify-write is
    flocked against concurrent provisions."""
    del provider_config
    with _ports_lock():
        state = opened_ports()
        state[cluster_name] = sorted(set(int(p) for p in ports))
        _ports_path().write_text(json.dumps(state, indent=2))


def cleanup_ports(cluster_name: str, ports: List[int],
                  provider_config: Optional[Dict] = None) -> None:
    del ports, provider_config
    with _ports_lock():
        state = opened_ports()
        if cluster_name in state:
            del state[cluster_name]
            _ports_path().write_text(json.dumps(state, indent=2))
