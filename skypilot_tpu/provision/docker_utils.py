"""Docker container runtime: `image_id: docker:<image>` support.

Reference: sky/provision/docker_utils.py (DockerInitializer, 447 LoC) +
sky/provision/provisioner.py:455 (docker init step). The reference
pulls the user image on each VM, starts one long-lived container, and
rewrites the cluster's command runners so every later operation
(runtime sync, job exec, log streaming) happens INSIDE the container.
Same design here, but as a runner-spec rewrite: after provisioning,
each host's runner_spec is wrapped in a `docker` spec
(utils/command_runner.DockerCommandRunner) that routes run/rsync
through `docker exec` / `docker cp`, so no other subsystem knows
containers exist — the agent daemon, gang executor, and log sync all
ride the same CommandRunner contract.

TPU note: the container runs --privileged with the host network, which
is what gives it the TPU device nodes (/dev/accel*) and the VM's
libtpu-visible identity — a torch-xla/JAX image then sees the chips
exactly as the host would.
"""
from __future__ import annotations

import shlex
from typing import Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.utils import command_runner
from skypilot_tpu.utils import subprocess_utils
from skypilot_tpu.utils import timeline

logger = sky_logging.init_logger(__name__)

DOCKER_PREFIX = 'docker:'
CONTAINER_NAME = 'skyt-container'


def is_docker_image(image_id: Optional[str]) -> bool:
    return bool(image_id) and image_id.startswith(DOCKER_PREFIX)


def image_name(image_id: str) -> str:
    return image_id[len(DOCKER_PREFIX):]


@timeline.event
def initialize_docker_on_cluster(info, image: str) -> None:
    """Pull `image` + start the long-lived container on every host, then
    swap each host's runner_spec to the docker wrapper. Idempotent: an
    existing container (cluster reuse / recovery relaunch) is replaced
    so the image is always the requested one."""
    img = shlex.quote(image)

    def _init_host(host) -> None:
        runner = command_runner.runner_from_spec(host.runner_spec)
        rc, _, err = runner.run('docker --version', require_outputs=True)
        if rc != 0:
            raise exceptions.ProvisionError(
                f'image_id {DOCKER_PREFIX}{image} needs docker on the '
                f'host image, but `docker --version` failed: {err[:200]}',
                scope=exceptions.FailoverScope.CLOUD, retryable=False)
        # Pull only when missing (inspect is local + fast on reuse).
        runner.run(
            f'docker image inspect {img} >/dev/null 2>&1 '
            f'|| docker pull {img}', check=True)
        runner.run(
            f'docker rm -f {CONTAINER_NAME} >/dev/null 2>&1 || true',
            check=False)
        # --network host + --privileged: TPU device nodes and the VM's
        # network identity (coordinator ports) are visible in-container.
        # --entrypoint overrides any image ENTRYPOINT (serving images
        # exec their server otherwise and the idle container dies).
        runner.run(
            f'docker run -d --name {CONTAINER_NAME} --network host '
            f'--privileged --entrypoint /bin/sh {img} '
            f"-c 'sleep infinity'", check=True)
        host.runner_spec = {'kind': 'docker',
                            'container': CONTAINER_NAME,
                            'inner': dict(host.runner_spec)}

    subprocess_utils.run_in_parallel(_init_host, info.sorted_instances())
    logger.info('Docker runtime %s initialized on %d host(s).', image,
                len(info.instances))
