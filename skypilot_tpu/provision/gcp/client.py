"""Minimal GCP REST client with pluggable transport + credentials.

The reference leans on googleapiclient discovery documents
(sky/adaptors/gcp.py, sky/provision/gcp/config.py:99-105). We talk REST
directly with urllib: fewer moving parts, no SDK dependency, and the
transport is injectable so the whole provider is unit-testable offline
(SURVEY.md §4 notes the reference cannot test its providers without live
clouds).

Credential chain (first hit wins):
  1. injected token via `set_token_provider` (tests),
  2. `GOOGLE_OAUTH_ACCESS_TOKEN` env var,
  3. `gcloud auth print-access-token`,
  4. GCE/TPU-VM metadata server (when running on a controller VM).
"""
from __future__ import annotations

import json
import os
import shutil
import subprocess
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging

logger = sky_logging.init_logger(__name__)

_METADATA_TOKEN_URL = ('http://metadata.google.internal/computeMetadata/v1/'
                       'instance/service-accounts/default/token')
_METADATA_PROJECT_URL = ('http://metadata.google.internal/computeMetadata/'
                         'v1/project/project-id')


class GcpApiError(Exception):
    """HTTP-level failure from a GCP API, with parsed error body."""

    def __init__(self, status: int, reason: str, message: str,
                 body: Optional[Dict[str, Any]] = None) -> None:
        super().__init__(f'GCP API error {status} ({reason}): {message}')
        self.status = status
        self.reason = reason
        self.message = message
        self.body = body or {}


def classify_api_error(err: GcpApiError, zone: str) -> exceptions.ProvisionError:
    """Map a GCP API failure to a typed failover error.

    Behavioral spec: FailoverCloudErrorHandlerV2._gcp_handler
    (cloud_vm_ray_backend.py:968-1123) — stockouts blocklist the zone,
    quota problems the region, auth/config problems the cloud. Quota is
    checked before capacity: a RESOURCE_EXHAUSTED quota message must
    blocklist the region, not one zone.
    """
    msg = err.message.lower()
    where = f' (zone {zone})' if zone else ''
    if 'quota' in msg:
        return exceptions.QuotaExceededError(err.message + where)
    if err.status == 429 or 'no more capacity' in msg or 'stockout' in msg or (
            'resource_exhausted' in msg or 'out of capacity' in msg or
            'not enough resources' in msg):
        return exceptions.TpuCapacityError(err.message + where)
    if err.status in (401, 403):
        return exceptions.ProvisionError(
            err.message, scope=exceptions.FailoverScope.CLOUD,
            retryable=False)
    if err.status == 409:  # already exists / concurrent op
        return exceptions.ProvisionError(err.message + where, retryable=True)
    return exceptions.ProvisionError(err.message + where)


# LRO errors carry google.rpc.Status canonical codes, not HTTP statuses;
# translate before classification so the 429/403 branches fire.
_GRPC_TO_HTTP = {3: 400, 5: 404, 6: 409, 7: 403, 8: 429, 9: 400,
                 13: 500, 14: 503, 16: 401}


def grpc_code_to_http(code: int) -> int:
    if code >= 100:  # already an HTTP status
        return code
    return _GRPC_TO_HTTP.get(code, 500)


# --------------------------------------------------------------------- #
# Transport + token injection (tests swap these out)
# --------------------------------------------------------------------- #

# transport(method, url, headers, body_bytes|None, timeout) -> (status, body)
Transport = Callable[[str, str, Dict[str, str], Optional[bytes], float],
                     'tuple[int, bytes]']

_transport: Optional[Transport] = None
_token_provider: Optional[Callable[[], str]] = None


def set_transport(transport: Optional[Transport]) -> None:
    global _transport
    _transport = transport


def set_token_provider(provider: Optional[Callable[[], str]]) -> None:
    global _token_provider
    _token_provider = provider


def _urllib_transport(method: str, url: str, headers: Dict[str, str],
                      body: Optional[bytes], timeout: float):
    req = urllib.request.Request(url, data=body, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


# --------------------------------------------------------------------- #
# Credentials
# --------------------------------------------------------------------- #

def _maybe_on_gce() -> bool:
    """Cheap local check before probing the metadata server: off-GCE the
    DNS lookup for metadata.google.internal can blackhole for seconds."""
    return (os.path.exists('/sys/class/dmi/id/product_name') and
            'Google' in pathlib_read('/sys/class/dmi/id/product_name'))


def pathlib_read(path: str) -> str:
    try:
        with open(path, encoding='utf-8', errors='replace') as f:
            return f.read()
    except OSError:
        return ''


_cached_token: Optional[str] = None
_cached_token_time: float = 0.0
_TOKEN_TTL_S = 600.0


def get_access_token() -> str:
    global _cached_token, _cached_token_time
    if _token_provider is not None:
        return _token_provider()
    if _cached_token and time.time() - _cached_token_time < _TOKEN_TTL_S:
        return _cached_token
    token = os.environ.get('GOOGLE_OAUTH_ACCESS_TOKEN')
    if not token and shutil.which('gcloud'):
        try:
            proc = subprocess.run(
                ['gcloud', 'auth', 'print-access-token'],
                capture_output=True, timeout=15, check=False)
            if proc.returncode == 0:
                token = proc.stdout.decode().strip()
        except subprocess.TimeoutExpired:
            token = None
    if not token and _maybe_on_gce():
        try:
            status, body = _urllib_transport(
                'GET', _METADATA_TOKEN_URL,
                {'Metadata-Flavor': 'Google'}, None, 2.0)
            if status == 200:
                token = json.loads(body)['access_token']
        except OSError:
            token = None
    if not token:
        raise exceptions.NoCloudAccessError(
            'No GCP credentials found. Set GOOGLE_OAUTH_ACCESS_TOKEN, '
            'install gcloud, or run on a GCE/TPU VM.')
    _cached_token, _cached_token_time = token, time.time()
    return token


def gcloud_config_value(key: str) -> Optional[str]:
    """`gcloud config get-value <key>`, or None (no gcloud / unset /
    timeout). Shared by project-id and OS Login account resolution."""
    if not shutil.which('gcloud'):
        return None
    try:
        proc = subprocess.run(
            ['gcloud', 'config', 'get-value', key],
            capture_output=True, timeout=15, check=False)
    except subprocess.TimeoutExpired:
        return None
    value = proc.stdout.decode().strip()
    if proc.returncode != 0 or not value or value == '(unset)':
        return None
    return value


def get_project_id(provider_config: Optional[Dict[str, Any]] = None) -> str:
    if provider_config and provider_config.get('project_id'):
        return provider_config['project_id']
    env = os.environ.get('GOOGLE_CLOUD_PROJECT') or os.environ.get(
        'GCP_PROJECT')
    if env:
        return env
    value = gcloud_config_value('project')
    if value:
        return value
    if _maybe_on_gce():
        try:
            status, body = _urllib_transport(
                'GET', _METADATA_PROJECT_URL,
                {'Metadata-Flavor': 'Google'}, None, 2.0)
            if status == 200:
                return body.decode().strip()
        except OSError:
            pass
    raise exceptions.NoCloudAccessError(
        'Could not determine GCP project id; set GOOGLE_CLOUD_PROJECT or '
        'pass provider_config.project_id.')


# --------------------------------------------------------------------- #
# Request
# --------------------------------------------------------------------- #

def request(method: str, url: str, body: Optional[Dict[str, Any]] = None,
            timeout: float = 60.0) -> Dict[str, Any]:
    """One authenticated JSON request. Raises GcpApiError on HTTP errors."""
    transport = _transport or _urllib_transport
    headers = {
        'Authorization': f'Bearer {get_access_token()}',
        'Content-Type': 'application/json',
    }
    data = json.dumps(body).encode() if body is not None else None
    status, raw = transport(method, url, headers, data, timeout)
    parsed: Dict[str, Any] = {}
    if raw:
        try:
            parsed = json.loads(raw)
        except json.JSONDecodeError:
            parsed = {'raw': raw.decode(errors='replace')}
    if status >= 400:
        err = parsed.get('error', {}) if isinstance(parsed, dict) else {}
        raise GcpApiError(
            status=status,
            reason=err.get('status', str(status)),
            message=err.get('message', str(parsed)[:500]),
            body=parsed)
    return parsed
