"""GCP provider protocol: TPU-VM slices + GCE VMs behind one interface.

Reference equivalent: sky/provision/gcp/instance.py (dispatch by
`_has_tpus` at :73-75) + instance_utils.py handler hierarchy. Here the
dispatch is typed — `config.resources.tpu` decides TPU vs GCE — and a
multi-host pod slice fans out to one InstanceInfo per networkEndpoint
(reference: instance_utils.py:1635-1655), which IS the SSH target list
and the jax.distributed process-rank ordering.

Cluster naming: a cluster of N TPU nodes creates TPU resources
`<cluster>-<i>`; GCE clusters create instances `<cluster>-<i>`.
"""
from __future__ import annotations

import re
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gcp import client
from skypilot_tpu.provision.gcp import compute_api
from skypilot_tpu.provision.gcp import tpu_api

logger = sky_logging.init_logger(__name__)

PROVIDER_NAME = 'gcp'

_LABEL_CLUSTER = 'skypilot-tpu-cluster'


def _safe_name(cluster_name: str) -> str:
    # GCP resource names: lowercase RFC1035.
    return re.sub(r'[^a-z0-9-]', '-', cluster_name.lower())


def _node_name(cluster_name: str, idx: int) -> str:
    return f'{_safe_name(cluster_name)}-{idx}'


def _is_tpu(config_or_provider: Any) -> bool:
    if isinstance(config_or_provider, common.ProvisionConfig):
        return config_or_provider.resources.tpu is not None
    return bool(config_or_provider.get('is_tpu'))


# --------------------------------------------------------------------- #
# Protocol
# --------------------------------------------------------------------- #

def bootstrap_config(config: common.ProvisionConfig) -> common.ProvisionConfig:
    """Resolve project + record provider metadata needed by later calls.

    The reference's bootstrap (gcp/config.py) creates IAM/VPC/firewall
    up-front; we rely on the default network + default service account and
    only create firewall rules when `ports:` asks for them.
    """
    from skypilot_tpu import config as config_lib
    project = client.get_project_id(config.provider_config)
    # OS Login (reference: sky/authentication.py:149): explicit config
    # wins; otherwise auto-detect the project's enable-oslogin metadata.
    # When active, import the framework key into the caller's profile
    # and SSH as the profile's POSIX username.
    use_oslogin = config.provider_config.get(
        'use_oslogin', config_lib.get_nested(['gcp', 'use_oslogin'], None))
    if use_oslogin is None:
        try:
            from skypilot_tpu.provision.gcp import oslogin
            use_oslogin = oslogin.project_oslogin_enabled(project)
        except Exception:  # noqa: BLE001 — metadata probe is best-effort
            use_oslogin = False
    if use_oslogin:
        from skypilot_tpu import exceptions as exc
        from skypilot_tpu.provision.gcp import oslogin
        try:
            posix_user = oslogin.import_ssh_key(
                config.authentication.get('ssh_public_key', ''))
        except client.GcpApiError as e:
            # Typed, so the failover loop handles it (transient 429/503
            # retries elsewhere; 401/403 is cloud-fatal).
            raise client.classify_api_error(e, config.zone) from e
        except exc.NoCloudAccessError as e:
            raise exc.ProvisionError(
                str(e), scope=exc.FailoverScope.CLOUD,
                retryable=False) from e
        config.authentication['ssh_user'] = posix_user
        logger.info(f'OS Login active: SSH as {posix_user!r}.')
    reservation = config.provider_config.get(
        'reservation',
        config_lib.get_nested(['gcp', 'specific_reservation'], None))
    config.provider_config.update({
        'project_id': project,
        'zone': config.zone,
        'is_tpu': config.resources.tpu is not None,
        'num_nodes': config.num_nodes,
        'ssh_user': config.authentication.get('ssh_user', 'skyt'),
        'ssh_key_path': config.authentication.get('ssh_private_key', ''),
        'use_oslogin': bool(use_oslogin),
        'reservation': reservation,
        'use_queued_resources': config.provider_config.get(
            'use_queued_resources',
            bool(config.resources.tpu is not None and
                 config.resources.tpu.is_pod)),
        'provision_timeout': config.provider_config.get(
            'provision_timeout',
            config_lib.get_nested(['gcp', 'provision_timeout'], None)),
    })
    return config


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    project = config.provider_config['project_id']
    zone = config.zone
    res = config.resources
    labels = dict(config.labels)
    labels[_LABEL_CLUSTER] = _safe_name(config.cluster_name)
    auth = config.authentication
    created: List[str] = []
    resumed: List[str] = []

    try:
        reservation = config.provider_config.get('reservation')
        if res.tpu is not None:
            body = tpu_api.node_body(
                tpu_type=res.tpu.accelerator_type,
                runtime_version=(res.runtime_version or
                                 res.tpu.default_runtime_version),
                ssh_user=auth['ssh_user'],
                ssh_public_key=auth['ssh_public_key'],
                labels=labels,
                use_spot=res.use_spot,
                network=config.provider_config.get('network'),
                subnetwork=config.provider_config.get('subnetwork'),
                use_oslogin=config.provider_config.get('use_oslogin',
                                                       False),
                reserved=bool(reservation))
            use_qr = config.provider_config.get('use_queued_resources')
            for i in range(config.num_nodes):
                name = _node_name(config.cluster_name, i)
                existing = _get_tpu_or_none(project, zone, name)
                if existing is not None:
                    state = existing.get('state')
                    if state == 'STOPPED':
                        op = tpu_api.start_node(project, zone, name)
                        tpu_api.wait_operation(op)
                        resumed.append(name)
                    elif state in ('READY', 'CREATING'):
                        resumed.append(name)
                    else:
                        raise exceptions.ProvisionError(
                            f'TPU {name} in unexpected state {state}')
                    continue
                if use_qr:
                    timeout = config.provider_config.get(
                        'provision_timeout')
                    tpu_api.create_queued_resource(
                        project, zone, qr_id=name, node_id=name,
                        body=body, use_spot=res.use_spot,
                        reserved=bool(reservation),
                        valid_until_duration_s=(int(timeout)
                                                if timeout else None))
                    tpu_api.wait_queued_resource(
                        project, zone, name,
                        timeout_s=float(timeout) if timeout else 1800.0)
                else:
                    op = tpu_api.create_node(project, zone, name, body)
                    tpu_api.wait_operation(op)
                created.append(name)
        else:
            machine_type = res.instance_type or 'n2-standard-8'
            for i in range(config.num_nodes):
                name = _node_name(config.cluster_name, i)
                existing = _get_gce_or_none(project, zone, name)
                if existing is not None:
                    if existing.get('status') == 'TERMINATED':
                        op = compute_api.start_instance(project, zone, name)
                        compute_api.wait_zone_operation(project, zone, op)
                        resumed.append(name)
                    else:
                        resumed.append(name)
                    continue
                body = compute_api.instance_body(
                    project, zone, name, machine_type,
                    ssh_user=auth['ssh_user'],
                    ssh_public_key=auth['ssh_public_key'],
                    use_oslogin=config.provider_config.get(
                        'use_oslogin', False),
                    reservation=reservation,
                    labels=labels,
                    disk_size_gb=res.disk_size_gb,
                    use_spot=res.use_spot,
                    network=config.provider_config.get(
                        'network', 'global/networks/default'))
                op = compute_api.insert_instance(project, zone, body)
                compute_api.wait_zone_operation(project, zone, op)
                created.append(name)
    except client.GcpApiError as e:
        raise client.classify_api_error(e, zone) from e

    if config.ports:
        open_ports(config.cluster_name, config.ports,
                   config.provider_config)

    return common.ProvisionRecord(
        provider_name=PROVIDER_NAME, cluster_name=config.cluster_name,
        region=config.region, zone=zone,
        resumed_instance_ids=resumed, created_instance_ids=created)


def _get_tpu_or_none(project: str, zone: str,
                     name: str) -> Optional[Dict[str, Any]]:
    try:
        return tpu_api.get_node(project, zone, name)
    except client.GcpApiError as e:
        if e.status == 404:
            return None
        raise


def _get_gce_or_none(project: str, zone: str,
                     name: str) -> Optional[Dict[str, Any]]:
    try:
        return compute_api.get_instance(project, zone, name)
    except client.GcpApiError as e:
        if e.status == 404:
            return None
        raise


def wait_instances(region: str, cluster_name: str,
                   state: Optional[str] = None,
                   provider_config: Optional[Dict[str, Any]] = None
                   ) -> None:
    """run_instances already waits on the create/start LROs; TPU READY and
    GCE RUNNING are reached before it returns."""
    del region, cluster_name, state


def _each_node(provider_config: Dict[str, Any], cluster_name: str):
    project = provider_config['project_id']
    zone = provider_config['zone']
    for i in range(int(provider_config.get('num_nodes', 1))):
        yield project, zone, _node_name(cluster_name, i)


def stop_instances(cluster_name: str,
                   provider_config: Optional[Dict] = None) -> None:
    pc = provider_config or {}
    for project, zone, name in _each_node(pc, cluster_name):
        try:
            if _is_tpu(pc):
                node = _get_tpu_or_none(project, zone, name)
                if node is None:
                    continue
                # Multi-host slices cannot stop (reference gates this at
                # clouds/gcp.py:193-197); callers should have routed pods
                # to terminate. Guard anyway.
                if len(node.get('networkEndpoints', [])) > 1:
                    raise exceptions.NotSupportedError(
                        f'TPU pod slice {name} cannot be stopped; use '
                        'down (autostop means autodown for pods).')
                op = tpu_api.stop_node(project, zone, name)
                tpu_api.wait_operation(op)
            else:
                if _get_gce_or_none(project, zone, name) is None:
                    continue
                op = compute_api.stop_instance(project, zone, name)
                compute_api.wait_zone_operation(project, zone, op)
        except client.GcpApiError as e:
            raise client.classify_api_error(e, zone) from e


def terminate_instances(cluster_name: str,
                        provider_config: Optional[Dict] = None) -> None:
    pc = provider_config or {}
    for project, zone, name in _each_node(pc, cluster_name):
        try:
            if _is_tpu(pc):
                if pc.get('use_queued_resources'):
                    try:
                        tpu_api.delete_queued_resource(project, zone, name)
                    except client.GcpApiError as e:
                        if e.status != 404:
                            raise
                if _get_tpu_or_none(project, zone, name) is not None:
                    op = tpu_api.delete_node(project, zone, name)
                    tpu_api.wait_operation(op)
            else:
                if _get_gce_or_none(project, zone, name) is not None:
                    op = compute_api.delete_instance(project, zone, name)
                    compute_api.wait_zone_operation(project, zone, op)
        except client.GcpApiError as e:
            raise client.classify_api_error(e, zone) from e
    cleanup_ports(cluster_name, [], provider_config)


_TPU_STATE_MAP = {
    'CREATING': common.InstanceStatus.PENDING,
    'STARTING': common.InstanceStatus.PENDING,
    'RESTARTING': common.InstanceStatus.PENDING,
    'READY': common.InstanceStatus.RUNNING,
    'STOPPING': common.InstanceStatus.STOPPED,
    'STOPPED': common.InstanceStatus.STOPPED,
    'DELETING': common.InstanceStatus.TERMINATED,
    'PREEMPTED': common.InstanceStatus.TERMINATED,
    'TERMINATED': common.InstanceStatus.TERMINATED,
}

_GCE_STATE_MAP = {
    'PROVISIONING': common.InstanceStatus.PENDING,
    'STAGING': common.InstanceStatus.PENDING,
    'RUNNING': common.InstanceStatus.RUNNING,
    'STOPPING': common.InstanceStatus.STOPPED,
    'SUSPENDING': common.InstanceStatus.STOPPED,
    'SUSPENDED': common.InstanceStatus.STOPPED,
    'TERMINATED': common.InstanceStatus.STOPPED,  # GCE TERMINATED = stopped
}


def query_instances(cluster_name: str,
                    provider_config: Optional[Dict] = None
                    ) -> Dict[str, str]:
    """instance name -> normalized status. Missing nodes are omitted —
    the reconciliation state machine treats disappearance as external
    termination/preemption (design_docs/cluster_status.md)."""
    pc = provider_config or {}
    out: Dict[str, str] = {}
    for project, zone, name in _each_node(pc, cluster_name):
        try:
            if _is_tpu(pc):
                node = _get_tpu_or_none(project, zone, name)
                if node is not None:
                    out[name] = _TPU_STATE_MAP.get(
                        node.get('state', ''),
                        common.InstanceStatus.PENDING)
            else:
                inst = _get_gce_or_none(project, zone, name)
                if inst is not None:
                    out[name] = _GCE_STATE_MAP.get(
                        inst.get('status', ''),
                        common.InstanceStatus.PENDING)
        except client.GcpApiError as e:
            raise client.classify_api_error(e, zone) from e
    return out


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Optional[Dict] = None
                     ) -> common.ClusterInfo:
    pc = provider_config or {}
    ssh_user = pc.get('ssh_user', 'skyt')
    key_path = pc.get('ssh_key_path', '~/.skypilot_tpu/keys/skyt.pem')
    instances: List[common.InstanceInfo] = []
    node_idx = -1
    for project, zone, name in _each_node(pc, cluster_name):
        node_idx += 1
        if _is_tpu(pc):
            node = _get_tpu_or_none(project, zone, name)
            if node is None:
                continue
            endpoints = node.get('networkEndpoints', [])
            # One InstanceInfo per host: host_index IS the TPU worker id,
            # which is the order networkEndpoints[] lists them in
            # (reference: instance_utils.py:1635-1655).
            for host_idx, ep in enumerate(endpoints):
                internal = ep.get('ipAddress', '')
                external = ep.get('accessConfig', {}).get('externalIp')
                ip = external or internal
                instances.append(common.InstanceInfo(
                    instance_id=f'{name}-w{host_idx}',
                    internal_ip=internal, external_ip=external,
                    node_index=node_idx, host_index=host_idx,
                    tags={'tpu_node': name},
                    runner_spec={'kind': 'ssh', 'ip': ip,
                                 'ssh_user': ssh_user,
                                 'ssh_key_path': key_path}))
        else:
            inst = _get_gce_or_none(project, zone, name)
            if inst is None:
                continue
            nic = inst.get('networkInterfaces', [{}])[0]
            internal = nic.get('networkIP', '')
            access = nic.get('accessConfigs', [{}])
            external = access[0].get('natIP') if access else None
            ip = external or internal
            instances.append(common.InstanceInfo(
                instance_id=name, internal_ip=internal,
                external_ip=external, node_index=node_idx, host_index=0,
                runner_spec={'kind': 'ssh', 'ip': ip,
                             'ssh_user': ssh_user,
                             'ssh_key_path': key_path}))
    if not instances:
        raise exceptions.ClusterDoesNotExist(
            f'No instances found for {cluster_name} in {region}.')
    return common.ClusterInfo(
        provider_name=PROVIDER_NAME, cluster_name=cluster_name,
        region=region, zone=pc.get('zone', ''), instances=instances,
        ssh_user=ssh_user)


def open_ports(cluster_name: str, ports: List[int],
               provider_config: Optional[Dict] = None) -> None:
    pc = provider_config or {}
    project = pc.get('project_id') or client.get_project_id(pc)
    compute_api.open_ports(project, cluster_name, ports,
                           network=pc.get('network',
                                          'global/networks/default'))


def cleanup_ports(cluster_name: str, ports: List[int],
                  provider_config: Optional[Dict] = None) -> None:
    del ports
    pc = provider_config or {}
    try:
        project = pc.get('project_id') or client.get_project_id(pc)
    except exceptions.NoCloudAccessError:
        return
    compute_api.cleanup_ports(project, cluster_name)
