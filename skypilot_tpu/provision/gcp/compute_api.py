"""GCE compute REST wrapper — controller / CPU-task VMs + firewall ports.

Reference equivalent: GCPComputeInstance (gcp/instance_utils.py:311-977).
Only the subset the TPU-first framework needs: instances for jobs/serve
controllers and CPU tasks, firewall rules for `ports:`.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision.gcp import client

logger = sky_logging.init_logger(__name__)

_BASE = 'https://compute.googleapis.com/compute/v1'


def _zone_url(project: str, zone: str) -> str:
    return f'{_BASE}/projects/{project}/zones/{zone}'


def instance_body(project: str, zone: str, name: str, machine_type: str,
                  ssh_user: str, ssh_public_key: str,
                  labels: Dict[str, str],
                  disk_size_gb: int = 256,
                  image: str = ('projects/ubuntu-os-cloud/global/images/'
                                'family/ubuntu-2204-lts'),
                  use_spot: bool = False,
                  network: str = 'global/networks/default',
                  tags: Optional[List[str]] = None,
                  use_oslogin: bool = False,
                  reservation: Optional[str] = None) -> Dict[str, Any]:
    """`use_oslogin` switches key injection to the caller's OS Login
    profile (reference: sky/authentication.py:149); `reservation` pins
    the VM to a specific compute reservation (reference:
    gcp_utils.py:66-167 specific_reservations)."""
    if use_oslogin:
        metadata_items = [{'key': 'enable-oslogin', 'value': 'TRUE'}]
    else:
        metadata_items = [{'key': 'ssh-keys',
                           'value': f'{ssh_user}:{ssh_public_key}'}]
    body: Dict[str, Any] = {
        'name': name,
        'machineType': f'zones/{zone}/machineTypes/{machine_type}',
        'disks': [{
            'boot': True,
            'autoDelete': True,
            'initializeParams': {
                'sourceImage': image,
                'diskSizeGb': str(disk_size_gb),
            },
        }],
        'networkInterfaces': [{
            'network': network,
            'accessConfigs': [{'name': 'External NAT',
                               'type': 'ONE_TO_ONE_NAT'}],
        }],
        'metadata': {
            'items': metadata_items,
        },
        'labels': dict(labels),
        'tags': {'items': tags or ['skypilot-tpu']},
    }
    if use_spot:
        body['scheduling'] = {
            'provisioningModel': 'SPOT',
            'instanceTerminationAction': 'STOP',
        }
    if reservation and not use_spot:
        # Spot VMs cannot consume reservations; spot wins (same
        # precedence as the TPU paths).
        body['reservationAffinity'] = {
            'consumeReservationType': 'SPECIFIC_RESERVATION',
            'key': 'compute.googleapis.com/reservation-name',
            'values': [reservation],
        }
    return body


def insert_instance(project: str, zone: str,
                    body: Dict[str, Any]) -> Dict[str, Any]:
    return client.request('POST', f'{_zone_url(project, zone)}/instances',
                          body)


def get_instance(project: str, zone: str, name: str) -> Dict[str, Any]:
    return client.request(
        'GET', f'{_zone_url(project, zone)}/instances/{name}')


def delete_instance(project: str, zone: str, name: str) -> Dict[str, Any]:
    return client.request(
        'DELETE', f'{_zone_url(project, zone)}/instances/{name}')


def stop_instance(project: str, zone: str, name: str) -> Dict[str, Any]:
    return client.request(
        'POST', f'{_zone_url(project, zone)}/instances/{name}/stop', {})


def start_instance(project: str, zone: str, name: str) -> Dict[str, Any]:
    return client.request(
        'POST', f'{_zone_url(project, zone)}/instances/{name}/start', {})


def wait_zone_operation(project: str, zone: str, op: Dict[str, Any],
                        timeout_s: float = 600.0,
                        poll_s: float = 3.0) -> Dict[str, Any]:
    name = op.get('name', '')
    deadline = time.time() + timeout_s
    url = f'{_zone_url(project, zone)}/operations/{name}'
    while True:
        if op.get('status') == 'DONE':
            break
        if time.time() > deadline:
            raise TimeoutError(f'GCE operation {name} timed out')
        time.sleep(poll_s)
        op = client.request('GET', url)
    err = op.get('error', {}).get('errors', [])
    if err:
        first = err[0]
        api_err = client.GcpApiError(
            status=409 if 'EXISTS' in first.get('code', '') else 500,
            reason=first.get('code', ''),
            message=first.get('message', str(first)))
        raise client.classify_api_error(api_err, zone)
    return op


# --------------------------------------------------------------------- #
# Firewall (open_ports / cleanup_ports)
# --------------------------------------------------------------------- #

def _firewall_name(cluster_name: str) -> str:
    return f'skyt-{cluster_name}-ports'


def open_ports(project: str, cluster_name: str, ports: List[int],
               network: str = 'global/networks/default') -> None:
    body = {
        'name': _firewall_name(cluster_name),
        'network': network,
        'direction': 'INGRESS',
        'allowed': [{'IPProtocol': 'tcp',
                     'ports': [str(p) for p in ports]}],
        'sourceRanges': ['0.0.0.0/0'],
        'targetTags': ['skypilot-tpu'],
    }
    try:
        client.request(
            'POST', f'{_BASE}/projects/{project}/global/firewalls', body)
    except client.GcpApiError as e:
        if e.status != 409:
            raise
        # Rule exists: PATCH the allowed-ports list — the serve path
        # re-opens the controller rule with the UNION of live service
        # ports, so an update must actually land, not be swallowed.
        client.request(
            'PATCH', f'{_BASE}/projects/{project}/global/firewalls/'
            f'{_firewall_name(cluster_name)}', body)


def cleanup_ports(project: str, cluster_name: str) -> None:
    try:
        client.request(
            'DELETE', f'{_BASE}/projects/{project}/global/firewalls/'
            f'{_firewall_name(cluster_name)}')
    except client.GcpApiError as e:
        if e.status != 404:
            raise
