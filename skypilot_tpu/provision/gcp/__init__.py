"""GCP provider: TPU-VM pod slices (tpu.googleapis.com v2) + GCE VMs.

Reference equivalent: sky/provision/gcp/ (3720 LoC — instance.py,
config.py, instance_utils.py). Re-designed TPU-first: the TPU node is the
primary resource (GCE VMs exist only for controllers/CPU tasks), the REST
surface is a thin hand-rolled client (no googleapiclient discovery), and
capacity/quota failures surface as typed exceptions instead of stdout
scraping (FailoverCloudErrorHandlerV2, cloud_vm_ray_backend.py:968-1123).
"""
