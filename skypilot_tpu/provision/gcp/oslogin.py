"""OS Login key management (reference: sky/authentication.py:149 —
GCP projects with `enable-oslogin=TRUE` ignore per-instance ssh-keys
metadata; keys must be imported into the caller's OS Login profile and
SSH uses the profile's POSIX username instead of the local user).
"""
from __future__ import annotations

import os
from typing import Any, Dict, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision.gcp import client

logger = sky_logging.init_logger(__name__)

_BASE = 'https://oslogin.googleapis.com/v1'
# Imported keys expire; 10 days covers long launches and is re-imported
# on every provision (the reference imports with no expiry; bounded is
# safer for a shared project).
_KEY_TTL_USEC = 10 * 24 * 3600 * 1_000_000


def get_account_email() -> str:
    """The Google account whose OS Login profile owns the key."""
    email = os.environ.get('SKYT_GCP_ACCOUNT')
    if email:
        return email
    email = client.gcloud_config_value('account')
    if email:
        return email
    raise exceptions.NoCloudAccessError(
        'OS Login needs the Google account email; set SKYT_GCP_ACCOUNT '
        'or configure gcloud.')


def project_oslogin_enabled(project: str) -> bool:
    """Project-level enable-oslogin metadata (reference checks the same
    project metadata before choosing the key-injection path)."""
    proj = client.request(
        'GET',
        f'https://compute.googleapis.com/compute/v1/projects/{project}')
    items = proj.get('commonInstanceMetadata', {}).get('items', [])
    for item in items:
        if item.get('key', '').lower() == 'enable-oslogin':
            return str(item.get('value', '')).lower() == 'true'
    return False


def import_ssh_key(public_key_content: str,
                   expire_usec: Optional[int] = None) -> str:
    """Import the framework pubkey into the caller's OS Login profile;
    returns the profile's primary POSIX username (the ssh_user for every
    VM in the project)."""
    import time
    email = get_account_email()
    expiry = expire_usec or int(time.time() * 1e6) + _KEY_TTL_USEC
    resp = client.request(
        'POST', f'{_BASE}/users/{email}:importSshPublicKey',
        {'key': public_key_content, 'expirationTimeUsec': str(expiry)})
    profile: Dict[str, Any] = resp.get('loginProfile', {})
    accounts = profile.get('posixAccounts', [])
    for acct in accounts:
        if acct.get('primary'):
            return acct['username']
    if accounts:
        return accounts[0]['username']
    raise exceptions.ProvisionError(
        f'OS Login profile for {email} has no POSIX account.',
        scope=exceptions.FailoverScope.CLOUD, retryable=False)
