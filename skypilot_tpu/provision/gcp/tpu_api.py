"""Cloud TPU API v2 wrapper: nodes + queued resources + operations.

Reference equivalent: GCPTPUVMInstance (gcp/instance_utils.py:1191-1655) —
nodes().create/stop/delete with operation polling (:1212-1258) and
networkEndpoints[] fan-out (:1635-1655). Additions over the reference:
the queuedResources API (better pod availability than direct create) and
typed capacity errors instead of error-string scraping.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import sky_logging
from skypilot_tpu.provision.gcp import client

logger = sky_logging.init_logger(__name__)

_BASE = 'https://tpu.googleapis.com/v2'


def _parent(project: str, zone: str) -> str:
    return f'projects/{project}/locations/{zone}'


def node_body(tpu_type: str, runtime_version: str,
              ssh_user: str, ssh_public_key: str,
              labels: Dict[str, str],
              use_spot: bool = False,
              network: Optional[str] = None,
              subnetwork: Optional[str] = None,
              tags: Optional[List[str]] = None,
              startup_script: Optional[str] = None,
              use_oslogin: bool = False,
              reserved: bool = False) -> Dict[str, Any]:
    """Build the Node resource body for nodes.create.

    Key injection follows sky/authentication.py:149: per-node ssh-keys
    metadata normally, or the caller's OS Login profile when the project
    enforces it (then `use_oslogin` drops the metadata — it would be
    ignored — and the ssh user is the profile's POSIX name, resolved in
    bootstrap_config). `reserved` consumes a TPU reservation
    (reference: gcp_utils.py:66-167 reservation plumbing).
    """
    metadata: Dict[str, str] = {}
    if use_oslogin:
        # Explicit opt-in must ACTIVATE OS Login on the node, not just
        # drop the (ignored) ssh-keys item — otherwise neither key path
        # is live and every host is unreachable.
        metadata['enable-oslogin'] = 'TRUE'
    else:
        metadata['ssh-keys'] = f'{ssh_user}:{ssh_public_key}'
    if startup_script:
        metadata['startup-script'] = startup_script
    body: Dict[str, Any] = {
        'acceleratorType': tpu_type,
        'runtimeVersion': runtime_version,
        'networkConfig': {
            'enableExternalIps': True,
        },
        'metadata': metadata,
        'labels': dict(labels),
        'tags': tags or ['skypilot-tpu'],
    }
    if network:
        body['networkConfig']['network'] = network
    if subnetwork:
        body['networkConfig']['subnetwork'] = subnetwork
    if use_spot:
        body['schedulingConfig'] = {'spot': True}
    elif reserved:
        body['schedulingConfig'] = {'reserved': True}
    return body


def create_node(project: str, zone: str, node_id: str,
                body: Dict[str, Any]) -> Dict[str, Any]:
    url = (f'{_BASE}/{_parent(project, zone)}/nodes?nodeId={node_id}')
    return client.request('POST', url, body)


def get_node(project: str, zone: str, node_id: str) -> Dict[str, Any]:
    url = f'{_BASE}/{_parent(project, zone)}/nodes/{node_id}'
    return client.request('GET', url)


def delete_node(project: str, zone: str, node_id: str) -> Dict[str, Any]:
    url = f'{_BASE}/{_parent(project, zone)}/nodes/{node_id}'
    return client.request('DELETE', url)


def stop_node(project: str, zone: str, node_id: str) -> Dict[str, Any]:
    url = f'{_BASE}/{_parent(project, zone)}/nodes/{node_id}:stop'
    return client.request('POST', url, {})


def start_node(project: str, zone: str, node_id: str) -> Dict[str, Any]:
    url = f'{_BASE}/{_parent(project, zone)}/nodes/{node_id}:start'
    return client.request('POST', url, {})


# --------------------------------------------------------------------- #
# Queued resources — availability-friendly pod acquisition
# --------------------------------------------------------------------- #

def create_queued_resource(project: str, zone: str, qr_id: str,
                           node_id: str, body: Dict[str, Any],
                           use_spot: bool = False,
                           reserved: bool = False,
                           valid_until_duration_s: Optional[int] = None
                           ) -> Dict[str, Any]:
    node = dict(body)
    node.pop('schedulingConfig', None)  # tier is set on the QR, not the node
    qr: Dict[str, Any] = {
        'tpu': {
            'nodeSpec': [{
                'parent': _parent(project, zone),
                'nodeId': node_id,
                'node': node,
            }],
        },
    }
    if use_spot:
        qr['spot'] = {}
    else:
        # reserved=True consumes the project's TPU reservation
        # (reference: reservations plumbing, gcp_utils.py:66-167).
        qr['guaranteed'] = {'reserved': True} if reserved else {}
    if valid_until_duration_s:
        qr['queueingPolicy'] = {
            'validUntilDuration': f'{valid_until_duration_s}s'}
    url = (f'{_BASE}/{_parent(project, zone)}/queuedResources'
           f'?queuedResourceId={qr_id}')
    return client.request('POST', url, qr)


def get_queued_resource(project: str, zone: str,
                        qr_id: str) -> Dict[str, Any]:
    url = f'{_BASE}/{_parent(project, zone)}/queuedResources/{qr_id}'
    return client.request('GET', url)


def delete_queued_resource(project: str, zone: str,
                           qr_id: str) -> Dict[str, Any]:
    url = (f'{_BASE}/{_parent(project, zone)}/queuedResources/{qr_id}'
           '?force=true')
    return client.request('DELETE', url)


# --------------------------------------------------------------------- #
# Operations
# --------------------------------------------------------------------- #

def wait_operation(operation: Dict[str, Any], timeout_s: float = 900.0,
                   poll_s: float = 5.0) -> Dict[str, Any]:
    """Poll an LRO until done (reference polls at instance_utils.py:1212).

    The operation's terminal `error` is classified into a typed
    ProvisionError so the failover loop gets structure, not stdout.
    """
    name = operation.get('name', '')
    if not name or operation.get('done'):
        op = operation
    else:
        deadline = time.time() + timeout_s
        url = f'{_BASE}/{name}'
        while True:
            op = client.request('GET', url)
            if op.get('done'):
                break
            if time.time() > deadline:
                raise TimeoutError(f'GCP operation {name} timed out '
                                   f'after {timeout_s}s')
            time.sleep(poll_s)
    err = op.get('error')
    if err:
        api_err = client.GcpApiError(
            status=client.grpc_code_to_http(int(err.get('code', 500))),
            reason=str(err.get('code', '')),
            message=err.get('message', str(err)))
        zone = name.split('/locations/')[-1].split('/')[0] if name else ''
        raise client.classify_api_error(api_err, zone)
    return op


def wait_queued_resource(project: str, zone: str, qr_id: str,
                         timeout_s: float = 1800.0,
                         poll_s: float = 10.0) -> Dict[str, Any]:
    """Wait for a queued resource to become ACTIVE (node provisioned).

    FAILED / SUSPENDED states map to capacity errors so failover moves on
    rather than waiting out a stockout.
    """
    from skypilot_tpu import exceptions
    deadline = time.time() + timeout_s
    while True:
        qr = get_queued_resource(project, zone, qr_id)
        state = qr.get('state', {}).get('state', 'UNKNOWN')
        if state == 'ACTIVE':
            return qr
        if state in ('FAILED', 'SUSPENDED'):
            detail = qr.get('state', {}).get('stateInitiator', '')
            raise exceptions.TpuCapacityError(
                f'Queued resource {qr_id} entered {state} ({detail}) '
                f'in {zone}.')
        if time.time() > deadline:
            try:
                delete_queued_resource(project, zone, qr_id)
            except client.GcpApiError:
                pass
            raise exceptions.TpuCapacityError(
                f'Queued resource {qr_id} still {state} after '
                f'{timeout_s}s in {zone}; treating as stockout.')
        time.sleep(poll_s)
