"""Provision orchestration: bulk_provision + runtime setup + failover.

Reference equivalents: sky/provision/provisioner.py (bulk_provision :100,
wait_for_ssh :216-392, _post_provision_setup :394) and the failover engine
RetryingVmProvisioner (cloud_vm_ray_backend.py:1156-2156). The reference's
failover parses provider stdout into blocklists
(FailoverCloudErrorHandlerV1/V2); our providers raise typed ProvisionError
with a FailoverScope, so the loop here is just: try a zone, blocklist at the
error's scope, move to the next candidate. TPU stockouts (the common case)
arrive as TpuCapacityError -> zone-scoped.
"""
from __future__ import annotations

import dataclasses
import json
import os
import pathlib
import time
from typing import Dict, List, Optional, Set, Tuple

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import provision
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import sky_logging
from skypilot_tpu.agent import constants as agent_constants
from skypilot_tpu.agent import native
from skypilot_tpu.provision import common
from skypilot_tpu.utils import command_runner
from skypilot_tpu.utils import subprocess_utils
from skypilot_tpu.utils import timeline

logger = sky_logging.init_logger(__name__)

_PACKAGE_ROOT = pathlib.Path(__file__).resolve().parent.parent


@dataclasses.dataclass
class ProvisionResult:
    record: common.ProvisionRecord
    cluster_info: common.ClusterInfo
    resources: resources_lib.Resources   # concrete, zone-pinned
    # Provider bookkeeping filled by bootstrap_config; must accompany every
    # later provider call (stop/terminate/query/get_cluster_info).
    provider_config: Dict = dataclasses.field(default_factory=dict)


@timeline.event
def provision_with_failover(
        cluster_name: str,
        cloud: str,
        resources: resources_lib.Resources,
        num_nodes: int,
        candidates: List,           # catalog offerings, price-ascending
        ports: Optional[List[int]] = None) -> ProvisionResult:
    """Try candidate zones in order, blocklisting at the scope each failure
    names (reference: provision_with_retries, cloud_vm_ray_backend.py:1980).
    """
    private_key, public_key = authentication.get_or_generate_keys()
    # Providers embed the pubkey CONTENT in instance metadata (ssh-keys);
    # the path rides along for anything that needs the file itself.
    try:
        with open(public_key) as f:
            public_key_content = f.read().strip()
    except OSError as e:
        # Fail fast with the real cause — an empty key would 'provision'
        # fine and only surface minutes later as SSH-unreachable.
        raise exceptions.ProvisionError(
            f'Cannot read SSH public key {public_key}: {e}',
            scope=exceptions.FailoverScope.CLOUD, retryable=False) from e
    auth = {'ssh_user': os.environ.get('USER', 'skyt'),
            'ssh_private_key': private_key,
            'ssh_public_key': public_key_content,
            'ssh_public_key_path': public_key}

    blocked_zones: Set[str] = set()
    blocked_regions: Set[str] = set()
    failures: List[Exception] = []

    for cand in candidates:
        zone, region = cand.zone, cand.region
        if zone in blocked_zones or region in blocked_regions:
            continue
        config = common.ProvisionConfig(
            cluster_name=cluster_name, cloud=cloud, region=region,
            zone=zone, num_nodes=num_nodes, resources=resources,
            authentication=auth, ports=list(ports or []))
        try:
            logger.info(f'Provisioning {cluster_name!r} '
                        f'({num_nodes}x {resources}) in {zone}...')
            # Per-attempt sub-stage spans: launch->first-step wallclock
            # (BASELINE north-star 1) decomposes into bootstrap / create
            # / boot-wait per zone tried, not one opaque provision blob.
            with timeline.Event('provision.bootstrap', zone=zone):
                config = provision.bootstrap_config(cloud, config)
            with timeline.Event('provision.run_instances', zone=zone):
                record = provision.run_instances(cloud, config)
            with timeline.Event('provision.wait_instances', zone=zone):
                provision.wait_instances(cloud, region, cluster_name,
                                         common.InstanceStatus.RUNNING,
                                         config.provider_config)
            info = provision.get_cluster_info(cloud, region, cluster_name,
                                              config.provider_config)
            # Ship the provider bookkeeping to the head (cluster_info
            # .json) so the daemon can autostop/terminate from inside.
            info.provider_config = config.provider_config
            concrete = resources.copy(cloud=cloud, region=region, zone=zone)
            return ProvisionResult(record=record, cluster_info=info,
                                   resources=concrete,
                                   provider_config=config.provider_config)
        except exceptions.ProvisionError as e:
            failures.append(e)
            logger.warning(f'  {zone}: {e}')
            # Clean partial state before moving on.
            try:
                provision.terminate_instances(cloud, cluster_name,
                                              config.provider_config)
            except Exception:  # noqa: BLE001
                pass
            if e.scope == exceptions.FailoverScope.ZONE:
                blocked_zones.add(zone)
            elif e.scope == exceptions.FailoverScope.REGION:
                blocked_regions.add(region)
            else:
                raise exceptions.ResourcesUnavailableError(
                    f'Cloud-level provisioning failure: {e}',
                    failover_history=failures) from e
    raise exceptions.ResourcesUnavailableError(
        f'Failed to provision {cluster_name!r} in all candidate zones '
        f'({len(failures)} attempts). Errors: '
        + '; '.join(str(f) for f in failures[-3:]),
        failover_history=failures, retryable=True)


# --------------------------------------------------------------------- #
# Post-provision runtime setup (reference: _post_provision_setup :394,
# instance_setup.py internal_file_mounts/setup_runtime_on_cluster)
# --------------------------------------------------------------------- #

@timeline.event
def wait_for_connectivity(info: common.ClusterInfo,
                          timeout: float = 600) -> None:
    """Block until every host answers (reference: wait_for_ssh :216)."""
    deadline = time.time() + timeout

    def _wait(host: common.InstanceInfo) -> None:
        runner = command_runner.runner_from_spec(host.runner_spec)
        while True:
            try:
                if runner.run('true', timeout=15) == 0:
                    return
            except Exception:  # noqa: BLE001
                pass
            if time.time() > deadline:
                raise exceptions.ProvisionError(
                    f'Host {host.instance_id} unreachable after '
                    f'{timeout}s', scope=exceptions.FailoverScope.ZONE)
            time.sleep(5)

    subprocess_utils.run_in_parallel(_wait, info.sorted_instances())


@timeline.event
def setup_runtime_on_cluster(info: common.ClusterInfo) -> None:
    """Ship the framework to every host + cluster_info to the head.

    Reference ships a locally-built wheel (wheel_utils.py:61-140) then
    pip-installs it; we rsync the package source into
    ~/.skyt_agent/runtime/skypilot_tpu — zero-install, python3 is enough.
    """
    hosts = info.sorted_instances()

    def _setup_host(host: common.InstanceInfo) -> None:
        runner = command_runner.runner_from_spec(host.runner_spec)
        runner.run(f'mkdir -p {agent_constants.RUNTIME_DIR} '
                   f'{agent_constants.JOBS_DIR} {agent_constants.LOGS_DIR}',
                   check=True)
        runner.rsync(str(_PACKAGE_ROOT) + '/',
                     f'{agent_constants.RUNTIME_DIR}/skypilot_tpu/',
                     up=True)
        # Build the native job supervisor (C++) on-host; best-effort.
        runner.run(native.remote_build_command(agent_constants.RUNTIME_DIR),
                   check=False)

    subprocess_utils.run_in_parallel(_setup_host, hosts)

    # Head gets cluster_info.json (how the gang executor reaches workers)
    # and the cluster private key for head->worker fan-out.
    head = info.head_instance
    head_runner = command_runner.runner_from_spec(head.runner_spec)
    info_json = json.dumps(info.to_dict())
    import tempfile
    with tempfile.TemporaryDirectory() as td:
        p = os.path.join(td, 'cluster_info.json')
        with open(p, 'w') as f:
            f.write(info_json)
        head_runner.rsync(p, agent_constants.CLUSTER_INFO, up=True)
        private_key, _ = authentication.get_or_generate_keys()
        if os.path.exists(private_key):
            head_runner.rsync(private_key,
                              f'{agent_constants.AGENT_HOME}/ssh_key',
                              up=True)
            head_runner.run(
                f'chmod 600 {agent_constants.AGENT_HOME}/ssh_key',
                check=False)


@timeline.event
def start_agent_daemon(info: common.ClusterInfo) -> None:
    """Start the head daemon (autostop + controller-liveness events;
    reference: skylet start, instance_setup.py:440). Idempotent via
    pidfile.

    The client's tuning env rides along (same set the controller RPCs
    forward): the daemon's scheduler/serve events spawn controller
    processes that inherit it — on the fake cloud they would otherwise
    lack SKYT_ENABLE_FAKE_CLOUD and fail their nested launches."""
    import shlex
    from skypilot_tpu.utils import controller_utils
    head_runner = command_runner.runner_from_spec(
        info.head_instance.runner_spec)
    pidfile = f'{agent_constants.AGENT_HOME}/daemon.pid'
    env_prefix = ' '.join(
        f'{k}={shlex.quote(v)}'
        for k, v in controller_utils.passthrough_envs().items())
    cmd = (
        f'if [ -f {pidfile} ] && kill -0 $(cat {pidfile}) 2>/dev/null; '
        f'then true; else '
        f'{env_prefix} PYTHONPATH={agent_constants.RUNTIME_DIR} '
        f'nohup python3 -m skypilot_tpu.agent.daemon '
        f'>> {agent_constants.AGENT_HOME}/daemon.log 2>&1 & '
        f'echo $! > {pidfile}; fi')
    head_runner.run(cmd, check=False)
