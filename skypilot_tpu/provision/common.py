"""Provisioner shared types (reference: sky/provision/common.py, 298 LoC)."""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from skypilot_tpu import resources as resources_lib


@dataclasses.dataclass
class ProvisionConfig:
    """Everything a provider impl needs to create one cluster.

    The reference renders a Jinja cluster YAML (backend_utils.py:691); we
    pass a typed config and let the provider map it to API calls. One
    `node` = one TPU slice (or one GCE VM for CPU clusters); a multi-host
    slice fans out to many InstanceInfos at query time.
    """
    cluster_name: str
    cloud: str
    region: str
    zone: str
    num_nodes: int
    resources: resources_lib.Resources
    authentication: Dict[str, str]          # ssh_user / public/private key
    ports: List[int] = dataclasses.field(default_factory=list)
    labels: Dict[str, str] = dataclasses.field(default_factory=dict)
    provider_config: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ProvisionRecord:
    """Result of run_instances (reference: common.py:63)."""
    provider_name: str
    cluster_name: str
    region: str
    zone: str
    resumed_instance_ids: List[str]
    created_instance_ids: List[str]

    def is_instance_just_booted(self, instance_id: str) -> bool:
        return (instance_id in self.resumed_instance_ids or
                instance_id in self.created_instance_ids)


@dataclasses.dataclass
class InstanceInfo:
    """One SSH target (reference: common.py:92). A v5p-64 node yields 8 of
    these — one per networkEndpoint (gcp/instance_utils.py:1635-1655)."""
    instance_id: str
    internal_ip: str
    external_ip: Optional[str]
    node_index: int        # which slice/VM this host belongs to
    host_index: int        # host rank within the slice (TPU worker id)
    tags: Dict[str, str] = dataclasses.field(default_factory=dict)
    # Serialized CommandRunner spec (utils/command_runner.runner_from_spec).
    runner_spec: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ClusterInfo:
    """Full cluster view returned by get_cluster_info (reference:
    common.py:109-230)."""
    provider_name: str
    cluster_name: str
    region: str
    zone: str
    instances: List[InstanceInfo]
    ssh_user: str = ''
    # Provider bookkeeping (api endpoints, project ids, namespaces) the
    # ON-CLUSTER daemon needs to call the provider from the inside
    # (autostop stop/terminate) — serialized into cluster_info.json.
    provider_config: Dict[str, Any] = dataclasses.field(
        default_factory=dict)

    @property
    def head_instance(self) -> InstanceInfo:
        return self.sorted_instances()[0]

    def sorted_instances(self) -> List[InstanceInfo]:
        """Stable global host ordering: (node_index, host_index). This IS
        the process-rank ordering for jax.distributed — not sorted-IP order
        (the reference sorts IPs, cloud_vm_ray_backend.py:381-556, which is
        wrong for TPU: rank must equal the TPU worker id)."""
        return sorted(self.instances,
                      key=lambda i: (i.node_index, i.host_index))

    @property
    def num_hosts(self) -> int:
        return len(self.instances)

    def to_dict(self) -> Dict[str, Any]:
        return {
            'provider_name': self.provider_name,
            'cluster_name': self.cluster_name,
            'region': self.region,
            'zone': self.zone,
            'ssh_user': self.ssh_user,
            'provider_config': self.provider_config,
            'instances': [dataclasses.asdict(i) for i in self.instances],
        }

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> 'ClusterInfo':
        insts = [InstanceInfo(**i) for i in d['instances']]
        return cls(provider_name=d['provider_name'],
                   cluster_name=d['cluster_name'], region=d['region'],
                   zone=d['zone'], instances=insts,
                   ssh_user=d.get('ssh_user', ''),
                   provider_config=d.get('provider_config', {}))


class InstanceStatus:
    """Provider-level instance states (normalized)."""
    PENDING = 'PENDING'
    RUNNING = 'RUNNING'
    STOPPED = 'STOPPED'
    TERMINATED = 'TERMINATED'
