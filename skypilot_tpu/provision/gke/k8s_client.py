"""Minimal Kubernetes REST client for the GKE provider.

The reference drives Kubernetes through the official SDK + kubectl
(sky/adaptors/kubernetes.py; sky/provision/kubernetes/, 5029 LoC). We
talk the API server's REST surface directly with the same injectable
transport/token pattern as provision/gcp/client.py, so the whole
provider is unit-testable offline.

Connection config comes from `provider_config` (or env fallbacks):
  * api_server: https://<GKE control plane IP>  (env SKYT_GKE_API_SERVER)
  * namespace:  pod namespace, default 'default'
Auth: GKE accepts the same Google OAuth bearer token as the other GCP
APIs, so credentials ride provision/gcp/client.get_access_token()
(env token / gcloud / metadata server).
"""
from __future__ import annotations

import json
import ssl
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, Optional

from skypilot_tpu.provision.gcp import client as gcp_client

Transport = Callable[[str, str, Dict[str, str], Optional[bytes], float],
                     'tuple[int, bytes]']

_transport: Optional[Transport] = None


def set_transport(transport: Optional[Transport]) -> None:
    global _transport
    _transport = transport


class K8sApiError(Exception):
    def __init__(self, status: int, reason: str, message: str) -> None:
        super().__init__(f'K8s API error {status} ({reason}): {message}')
        self.status = status
        self.reason = reason
        self.message = message


def _ssl_context() -> ssl.SSLContext:
    """Verified TLS by default — the bearer token is the user's FULL
    Google OAuth credential, so MITM here leaks everything. GKE control
    planes use a per-cluster CA: point SKYT_GKE_CA_CERT at its PEM
    (from `gcloud container clusters describe`). Only an explicit
    SKYT_GKE_INSECURE_SKIP_VERIFY=1 disables verification (dev)."""
    import os
    ca = os.environ.get('SKYT_GKE_CA_CERT')
    if ca:
        return ssl.create_default_context(cafile=os.path.expanduser(ca))
    ctx = ssl.create_default_context()
    if os.environ.get('SKYT_GKE_INSECURE_SKIP_VERIFY') == '1':
        ctx.check_hostname = False
        ctx.verify_mode = ssl.CERT_NONE
    return ctx


def _urllib_transport(method: str, url: str, headers: Dict[str, str],
                      body: Optional[bytes], timeout: float):
    req = urllib.request.Request(url, data=body, headers=headers,
                                 method=method)
    try:
        with urllib.request.urlopen(req, timeout=timeout,
                                    context=_ssl_context()) as resp:
            return resp.status, resp.read()
    except urllib.error.HTTPError as e:
        return e.code, e.read()


def request(api_server: str, method: str, path: str,
            body: Optional[Dict[str, Any]] = None,
            timeout: float = 60.0) -> Dict[str, Any]:
    transport = _transport or _urllib_transport
    headers = {
        'Authorization': f'Bearer {gcp_client.get_access_token()}',
        'Content-Type': 'application/json',
    }
    data = json.dumps(body).encode() if body is not None else None
    status, raw = transport(method, f'{api_server}{path}', headers, data,
                            timeout)
    parsed: Dict[str, Any] = {}
    if raw:
        try:
            parsed = json.loads(raw)
        except json.JSONDecodeError:
            parsed = {'raw': raw.decode(errors='replace')}
    if status >= 400:
        raise K8sApiError(status,
                          parsed.get('reason', str(status)),
                          parsed.get('message', str(parsed)[:300]))
    return parsed
