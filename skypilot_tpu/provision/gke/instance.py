"""GKE TPU pod-slice provider (the 11-function protocol of
provision/__init__.py against the Kubernetes API).

Reference: sky/provision/kubernetes/instance.py (+utils.py TPU label
formatters; smoke test tests/smoke_tests/test_cluster_job.py:578
`--gpus tpu-v5-lite-podslice`). The reference models one pod per
requested node and schedules TPUs via the `google.com/tpu` resource +
GKE's podslice node selectors; we keep that contract but emit it from
the typed TpuTopology instead of pseudo-accelerator names:

  * nodeSelector cloud.google.com/gke-tpu-accelerator: <podslice label>
  * nodeSelector cloud.google.com/gke-tpu-topology: <AxB | AxBxC>
  * resources google.com/tpu: <chips per host>

One framework "node" = one TPU slice; a multi-host slice fans out to
`num_hosts` pods (one per TPU host VM), named
`<cluster>-n<node>-h<host>`, plus one headless Service for stable DNS.
Pods cannot stop, so stop_instances raises and autostop means autodown
— the same semantics as TPU pod slices on plain GCP.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import sky_logging
from skypilot_tpu.provision import common
from skypilot_tpu.provision.gke import k8s_client

logger = sky_logging.init_logger(__name__)

PROVIDER_NAME = 'gke'

# TPU generation -> GKE podslice accelerator label
# (reference: kubernetes/utils.py label formatters; GKE docs).
GKE_TPU_ACCELERATORS = {
    'v4': 'tpu-v4-podslice',
    'v5e': 'tpu-v5-lite-podslice',
    'v5p': 'tpu-v5p-slice',
    'v6e': 'tpu-v6e-slice',
}

# chips -> gke-tpu-topology. v5e/v6e use 2D (4 chips/host grid);
# v4/v5p use 3D (4-chip hosts in a cube).
_TOPOLOGY_2D = {1: '1x1', 4: '2x2', 8: '2x4', 16: '4x4', 32: '4x8',
                64: '8x8', 128: '8x16', 256: '16x16'}
_TOPOLOGY_3D = {4: '2x2x1', 8: '2x2x2', 16: '2x2x4', 32: '2x4x4',
                64: '4x4x4', 128: '4x4x8', 256: '4x8x8', 512: '8x8x8',
                1024: '8x8x16', 2048: '8x16x16'}


def gke_topology_label(topo) -> str:
    table = (_TOPOLOGY_2D if topo.generation in ('v5e', 'v6e')
             else _TOPOLOGY_3D)
    label = table.get(topo.num_chips)
    if label is None:
        raise exceptions.InvalidResourcesError(
            f'{topo.type_name}: no GKE topology mapping for '
            f'{topo.num_chips} chips.')
    return label


def _cfg(provider_config: Optional[Dict]) -> Dict[str, Any]:
    import os
    cfg = dict(provider_config or {})
    cfg.setdefault('api_server', os.environ.get('SKYT_GKE_API_SERVER'))
    cfg.setdefault('namespace', 'default')
    cfg.setdefault('image', 'python:3.11-slim')
    if not cfg['api_server']:
        raise exceptions.NoCloudAccessError(
            'GKE provider needs an API server: set SKYT_GKE_API_SERVER '
            'or provider_config.api_server.')
    return cfg


def _pods_path(ns: str, name: str = '') -> str:
    return f'/api/v1/namespaces/{ns}/pods' + (f'/{name}' if name else '')


def _svc_path(ns: str, name: str = '') -> str:
    return (f'/api/v1/namespaces/{ns}/services'
            + (f'/{name}' if name else ''))


def _selector(cluster_name: str) -> str:
    return f'?labelSelector=skyt-cluster%3D{cluster_name}'


def _list_pods(cfg: Dict[str, Any], cluster_name: str) -> List[Dict]:
    resp = k8s_client.request(
        cfg['api_server'], 'GET',
        _pods_path(cfg['namespace']) + _selector(cluster_name))
    return resp.get('items', [])


def bootstrap_config(config: common.ProvisionConfig
                     ) -> common.ProvisionConfig:
    """Validate the TPU request maps to GKE labels; fill defaults."""
    config.provider_config.update(_cfg(config.provider_config))
    res = config.resources
    if res.tpu is not None:
        if res.tpu.generation not in GKE_TPU_ACCELERATORS:
            raise exceptions.InvalidResourcesError(
                f'GKE has no podslice node pools for TPU '
                f'{res.tpu.generation}.')
        gke_topology_label(res.tpu)  # raises if unmapped
    return config


def _pod_body(config: common.ProvisionConfig, pod_name: str,
              node_index: int, host_index: int) -> Dict[str, Any]:
    res = config.resources
    cfg = config.provider_config
    labels = {'skyt-cluster': config.cluster_name,
              'skyt-node': str(node_index),
              'skyt-host': str(host_index), **config.labels}
    spec: Dict[str, Any] = {
        'hostname': pod_name,
        'subdomain': config.cluster_name,
        'restartPolicy': 'Never',
        'containers': [{
            'name': 'skyt',
            'image': cfg['image'],
            'command': ['/bin/sh', '-c', 'sleep infinity'],
        }],
    }
    if res.tpu is not None:
        topo = res.tpu
        spec['nodeSelector'] = {
            'cloud.google.com/gke-tpu-accelerator':
                GKE_TPU_ACCELERATORS[topo.generation],
            'cloud.google.com/gke-tpu-topology': gke_topology_label(topo),
        }
        tpu_res = {'google.com/tpu': str(topo.chips_per_host)}
        spec['containers'][0]['resources'] = {'requests': tpu_res,
                                              'limits': tpu_res}
    return {'apiVersion': 'v1', 'kind': 'Pod',
            'metadata': {'name': pod_name, 'labels': labels},
            'spec': spec}


def run_instances(config: common.ProvisionConfig) -> common.ProvisionRecord:
    cfg = config.provider_config
    ns = cfg['namespace']
    api = cfg['api_server']
    res = config.resources
    hosts_per_node = res.num_hosts()
    existing = {p['metadata']['name'] for p in
                _list_pods(cfg, config.cluster_name)}
    created: List[str] = []
    # Headless service: stable DNS for host-to-host rendezvous
    # (<pod>.<cluster>.<ns>.svc), same role as TPU-VM internal IPs.
    try:
        k8s_client.request(api, 'POST', _svc_path(ns), {
            'apiVersion': 'v1', 'kind': 'Service',
            'metadata': {'name': config.cluster_name,
                         'labels': {'skyt-cluster': config.cluster_name}},
            'spec': {'clusterIP': 'None',
                     'selector': {'skyt-cluster': config.cluster_name}},
        })
    except k8s_client.K8sApiError as e:
        if e.status != 409:  # already exists on reuse
            raise _classify(e, config.zone)
    for node in range(config.num_nodes):
        for host in range(hosts_per_node):
            pod_name = f'{config.cluster_name}-n{node}-h{host}'
            if pod_name in existing:
                continue
            try:
                k8s_client.request(
                    api, 'POST', _pods_path(ns),
                    _pod_body(config, pod_name, node, host))
            except k8s_client.K8sApiError as e:
                raise _classify(e, config.zone)
            created.append(pod_name)
    return common.ProvisionRecord(
        provider_name=PROVIDER_NAME, cluster_name=config.cluster_name,
        region=config.region, zone=config.zone,
        resumed_instance_ids=[], created_instance_ids=created)


def _classify(e: k8s_client.K8sApiError, zone: str):
    """K8s failures -> typed failover errors (parallels
    gcp/client.classify_api_error): unschedulable TPU pods are capacity,
    quota'd namespaces are quota, auth is cloud-fatal."""
    msg = e.message.lower()
    if 'exceeded quota' in msg or e.reason == 'Forbidden' and 'quota' in msg:
        return exceptions.QuotaExceededError(e.message)
    if e.status in (401, 403):
        return exceptions.ProvisionError(
            e.message, scope=exceptions.FailoverScope.CLOUD,
            retryable=False)
    if 'insufficient' in msg or 'unschedulable' in msg:
        return exceptions.TpuCapacityError(e.message)
    return exceptions.ProvisionError(f'{e.message} (zone {zone})')


def wait_instances(region: str, cluster_name: str,
                   state: str = 'running',
                   provider_config: Optional[Dict] = None,
                   timeout: float = 600.0) -> None:
    """Block until every pod is Running (or gone, for state='terminated').
    An unschedulable pod (no TPU node pool capacity) surfaces as a
    TpuCapacityError so the failover engine can move on."""
    cfg = _cfg(provider_config)
    deadline = time.time() + timeout
    while True:
        pods = _list_pods(cfg, cluster_name)
        if state == 'terminated':
            if not pods:
                return
        else:
            phases = [p.get('status', {}).get('phase') for p in pods]
            if pods and all(ph == 'Running' for ph in phases):
                return
            for pod, phase in zip(pods, phases):
                # Fast-fail: Failed/Succeeded can never become Running
                # (restartPolicy=Never) — burning the full timeout would
                # delay failover to the next zone by minutes.
                if phase in ('Failed', 'Succeeded'):
                    raise exceptions.ProvisionError(
                        f'GKE pod {pod["metadata"]["name"]} entered '
                        f'terminal phase {phase} during provisioning.')
                for cond in pod.get('status', {}).get('conditions', []):
                    if (cond.get('reason') == 'Unschedulable'
                            and 'tpu' in str(cond.get('message', '')
                                             ).lower()):
                        raise exceptions.TpuCapacityError(
                            f'GKE cannot schedule '
                            f'{pod["metadata"]["name"]}: '
                            f'{cond.get("message")}')
        if time.time() > deadline:
            raise exceptions.ProvisionError(
                f'GKE pods for {cluster_name!r} not {state} after '
                f'{timeout}s')
        time.sleep(2)


def stop_instances(cluster_name: str,
                   provider_config: Optional[Dict] = None) -> None:
    raise exceptions.NotSupportedError(
        'GKE TPU pod slices cannot stop (no VM disks to preserve); '
        'use down instead.')


def terminate_instances(cluster_name: str,
                        provider_config: Optional[Dict] = None) -> None:
    cfg = _cfg(provider_config)
    api, ns = cfg['api_server'], cfg['namespace']
    for pod in _list_pods(cfg, cluster_name):
        try:
            k8s_client.request(api, 'DELETE',
                               _pods_path(ns, pod['metadata']['name']))
        except k8s_client.K8sApiError as e:
            if e.status != 404:
                raise
    for path in (_svc_path(ns, cluster_name),
                 _svc_path(ns, f'{cluster_name}-ports')):
        try:
            k8s_client.request(api, 'DELETE', path)
        except k8s_client.K8sApiError as e:
            if e.status != 404:
                raise


def query_instances(cluster_name: str,
                    provider_config: Optional[Dict] = None
                    ) -> Dict[str, str]:
    cfg = _cfg(provider_config)
    out: Dict[str, str] = {}
    for pod in _list_pods(cfg, cluster_name):
        phase = pod.get('status', {}).get('phase', 'Pending')
        status = {'Pending': common.InstanceStatus.PENDING,
                  'Running': common.InstanceStatus.RUNNING,
                  'Succeeded': common.InstanceStatus.TERMINATED,
                  'Failed': common.InstanceStatus.TERMINATED,
                  }.get(phase, common.InstanceStatus.PENDING)
        out[pod['metadata']['name']] = status
    return out


def get_cluster_info(region: str, cluster_name: str,
                     provider_config: Optional[Dict] = None
                     ) -> common.ClusterInfo:
    cfg = _cfg(provider_config)
    instances = []
    for pod in _list_pods(cfg, cluster_name):
        meta = pod['metadata']
        labels = meta.get('labels', {})
        instances.append(common.InstanceInfo(
            instance_id=meta['name'],
            internal_ip=pod.get('status', {}).get('podIP', ''),
            external_ip=None,
            node_index=int(labels.get('skyt-node', 0)),
            host_index=int(labels.get('skyt-host', 0)),
            tags=dict(labels),
            runner_spec={'kind': 'kubectl',
                         'namespace': cfg['namespace'],
                         'pod': meta['name'],
                         'container': 'skyt',
                         'context': cfg.get('context')}))
    if not instances:
        raise exceptions.ClusterDoesNotExist(cluster_name)
    return common.ClusterInfo(
        provider_name=PROVIDER_NAME, cluster_name=cluster_name,
        region=region, zone=region, instances=instances, ssh_user='root')


def open_ports(cluster_name: str, ports: List[int],
               provider_config: Optional[Dict] = None) -> None:
    """Expose ports via a LoadBalancer Service selecting the cluster's
    pods (the k8s-native analog of the GCE firewall rule)."""
    cfg = _cfg(provider_config)
    api, ns = cfg['api_server'], cfg['namespace']
    name = f'{cluster_name}-ports'
    body = {
        'apiVersion': 'v1', 'kind': 'Service',
        'metadata': {'name': name,
                     'labels': {'skyt-cluster': cluster_name}},
        'spec': {'type': 'LoadBalancer',
                 'selector': {'skyt-cluster': cluster_name},
                 'ports': [{'name': f'p{p}', 'port': int(p),
                            'targetPort': int(p)} for p in ports]},
    }
    try:
        k8s_client.request(api, 'POST', _svc_path(ns), body)
    except k8s_client.K8sApiError as e:
        if e.status != 409:
            raise
        # Replace must carry the live object's immutable fields
        # (spec.clusterIP and metadata.resourceVersion) or the API
        # server rejects the PUT with 422.
        live = k8s_client.request(api, 'GET', _svc_path(ns, name))
        live.setdefault('spec', {})['ports'] = body['spec']['ports']
        live['spec']['type'] = 'LoadBalancer'
        live['spec']['selector'] = body['spec']['selector']
        k8s_client.request(api, 'PUT', _svc_path(ns, name), live)


def cleanup_ports(cluster_name: str, ports: List[int],
                  provider_config: Optional[Dict] = None) -> None:
    del ports
    cfg = _cfg(provider_config)
    try:
        k8s_client.request(cfg['api_server'], 'DELETE',
                           _svc_path(cfg['namespace'],
                                     f'{cluster_name}-ports'))
    except k8s_client.K8sApiError as e:
        if e.status != 404:
            raise
